"""Serve fleet: journal claim/lease semantics, work-stealing workers.

The ISSUE-15 acceptance pins live here:

* claim arbitration is first-writer-wins over O_EXCL-atomic journal
  segments: concurrent appends never tear, losers observe the winner
  on replay and move on;
* leases expire and are reaped: a dead/frozen worker's in-flight job
  is re-claimed by a peer (the 2x-TTL bound rides the slow soak and
  the committed campaign artifact);
* a 2-worker subprocess fleet drains a shared journaled queue
  byte-identical to a single worker, zero lost / zero duplicated;
* journal replay is O(tail) via checkpoints, with compacted replay
  provably equal to full replay;
* resume-time output verification has a stat fast path with a
  ``--verify-outputs full`` escape hatch;
* the exposition carries ``worker`` labels lint-clean, and
  ``s2c_top --fleet`` renders an aggregated multi-worker frame.
"""

import json
import os
import subprocess
import sys
import time

import pytest

from sam2consensus_tpu.config import RunConfig
from sam2consensus_tpu.observability.metrics import MetricsRegistry
from sam2consensus_tpu.serve import journal as sjournal
from sam2consensus_tpu.serve.fleet import FleetCoordinator
from sam2consensus_tpu.utils.simulate import SimSpec, simulate

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_persistent_cache(monkeypatch):
    monkeypatch.setenv("S2C_JIT_CACHE", "")


def _journal(tmp_path, **kw):
    kw.setdefault("checkpoint_every", 0)   # deterministic segment sets
    return sjournal.JobJournal(str(tmp_path / "j"), **kw)


def _coord(j, worker, ttl=5.0):
    return FleetCoordinator(j, worker, ttl, MetricsRegistry())


def _sim(tmp, name, seed, contig_len=2500, n_reads=800, prefix="fl"):
    spec = SimSpec(n_contigs=1, contig_len=contig_len, n_reads=n_reads,
                   read_len=100, contig_len_jitter=0.0, seed=seed,
                   contig_prefix=prefix)
    path = os.path.join(str(tmp), name)
    with open(path, "w") as fh:
        fh.write(simulate(spec))
    return path


# =========================================================================
# claim / lease state machine
# =========================================================================
class TestClaims:
    def test_first_claim_wins_second_loses(self, tmp_path):
        j = _journal(tmp_path)
        a = _coord(j, "wa")
        b = _coord(sjournal.JobJournal(j.root, checkpoint_every=0),
                   "wb")
        assert a.try_claim("k1", "job1")
        assert not b.try_claim("k1", "job1")
        assert a.registry.value("fleet/claims") == 1
        # a LIVE peer lease is observed, not raced: b appends nothing
        assert b.registry.value("fleet/claims") == 0
        st = j.replay()
        assert st.claims["k1"]["worker"] == "wa"
        assert len([e for e in j.events()
                    if e["ev"] == "claimed"]) == 1

    def test_losing_claim_event_ignored_on_replay(self, tmp_path):
        j = _journal(tmp_path)
        now = time.time()
        j.append("claimed", key="k", worker="wa",
                 expires_unix=now + 60)
        j.append("claimed", key="k", worker="wb",
                 expires_unix=now + 60)
        st = j.replay()
        assert st.claims["k"]["worker"] == "wa"

    def test_commit_and_failure_close_the_lease(self, tmp_path):
        j = _journal(tmp_path)
        now = time.time()
        j.append("claimed", key="k", worker="wa",
                 expires_unix=now + 60)
        j.append("committed", key="k", job="x", outputs={},
                 worker="wa")
        assert "k" not in j.replay().claims
        j.append("claimed", key="k2", worker="wa",
                 expires_unix=now + 60)
        j.append("failed", key="k2", job="x", error="boom")
        assert "k2" not in j.replay().claims

    def test_expired_lease_is_reaped_and_stolen(self, tmp_path):
        j = _journal(tmp_path)
        a = _coord(j, "wa", ttl=0.05)
        b = _coord(sjournal.JobJournal(j.root, checkpoint_every=0),
                   "wb", ttl=5.0)
        assert a.try_claim("k", "job")
        time.sleep(0.08)
        assert b.try_claim("k", "job")        # reap + steal
        assert b.registry.value("fleet/steals") == 1
        assert b.registry.value("fleet/lease_reaped") == 1
        evs = [e["ev"] for e in j.events()]
        assert "lease_expired" in evs
        st = j.replay()
        assert st.claims["k"]["worker"] == "wb"
        # the frozen-then-woken original holder must see the loss
        assert not a.holds("k")
        assert "k" not in a.held

    def test_zombie_commit_is_fenced_void(self, tmp_path):
        """The split-brain TOCTOU closed structurally: a zombie whose
        pending 'committed' append lands AFTER the thief's commit is
        VOID on replay (wrong lease lineage), so commit_counts stays
        at 1 and the thief's record — whose fingerprints describe the
        files actually on disk — remains authoritative."""
        j = _journal(tmp_path)
        now = time.time()
        s_a = j.append("claimed", key="k", job="x", worker="wa",
                       expires_unix=now - 1.0)   # zombie's stale lease
        j.append("lease_expired", key="k", worker="wa", reaper="wb")
        s_b = j.append("claimed", key="k", job="x", worker="wb",
                       expires_unix=now + 60)
        j.append("committed", key="k", job="x", worker="wb",
                 claim_seq=s_b, outputs={"f": None})
        # the zombie wakes and its stale append lands LAST
        j.append("committed", key="k", job="x", worker="wa",
                 claim_seq=s_a, outputs={"stale": None})
        st = j.replay()
        assert st.commit_counts == {"k": 1}
        assert st.committed["k"]["worker"] == "wb"
        assert st.stale_commits == {"k": 1}
        audit = j.audit()
        assert audit["duplicated"] == []
        assert audit["stale_commits"] == {"k": 1}
        # serial-mode journals (no claims ever) stay unfenced: the
        # restart drift re-commit contract is unchanged
        j.append("committed", key="plain", job="y", outputs={})
        j.append("committed", key="plain", job="y", outputs={})
        assert j.replay().commit_counts["plain"] == 2

    def test_renewal_extends_and_voids_stale_reap(self, tmp_path):
        j = _journal(tmp_path)
        now = time.time()
        j.append("claimed", key="k", worker="wa",
                 expires_unix=now - 1.0)          # looks expired...
        j.append("lease_renewed", key="k", worker="wa",
                 expires_unix=now + 60.0)         # ...but renewed first
        # a reaper acting on the stale view appends lease_expired NOW;
        # its event time is < the renewed expiry, so it must be void
        j.append("lease_expired", key="k", worker="wa", reaper="wb")
        st = j.replay()
        assert st.claims["k"]["worker"] == "wa"
        assert st.claims["k"]["expires_unix"] == pytest.approx(
            now + 60.0, abs=0.01)

    def test_tick_renews_at_half_ttl(self, tmp_path):
        j = _journal(tmp_path)
        a = _coord(j, "wa", ttl=0.2)
        assert a.try_claim("k", "job")
        time.sleep(0.12)                          # past half-TTL
        a.tick()
        assert a.registry.value("fleet/lease_renewals") >= 1
        assert a.holds("k")

    def test_restart_adopts_own_claim(self, tmp_path):
        j = _journal(tmp_path)
        a = _coord(j, "wa", ttl=60.0)
        assert a.try_claim("k", "job")
        # same worker id, new process (the restart): adopt, not lose
        a2 = _coord(sjournal.JobJournal(j.root, checkpoint_every=0),
                    "wa", ttl=60.0)
        assert a2.try_claim("k", "job")
        assert a2.holds("k")

    def test_steal_happens_within_bound_in_process(self, tmp_path):
        """A non-renewing holder's job becomes claimable roughly at
        TTL; the hard 2x-TTL bound is pinned by the soak artifact —
        here we pin that the steal path works and is prompt."""
        j = _journal(tmp_path)
        a = _coord(j, "wa", ttl=0.3)
        b = _coord(sjournal.JobJournal(j.root, checkpoint_every=0),
                   "wb", ttl=0.3)
        assert a.try_claim("k", "job")
        t0 = time.monotonic()
        while not b.try_claim("k", "job"):
            time.sleep(0.02)
            assert time.monotonic() - t0 < 5.0
        assert b.holds("k")

    def test_fleet_burn_and_window_seed(self, tmp_path):
        j = _journal(tmp_path)
        j.append("submitted", key="k1", job="a", tenant="tb")
        j.append("submitted", key="k2", job="b", tenant="tb")
        j.append("started", key="k1", job="a", worker="wa",
                 tenant="tb")
        j.append("committed", key="k1", job="a", outputs={},
                 elapsed_sec=9.0, tenant="tb", worker="wa")
        st = j.replay()
        c = _coord(j, "wb")
        assert c.fleet_burn(st, {"e2e": 5.0}) == {"tb": 1}
        assert c.fleet_burn(st, {"e2e": 20.0}) == {}
        # k2 is live elsewhere (not ours, not terminal): seeds quota
        assert c.seed_window_counts(st, own_keys=set()) == {"tb": 1}
        assert c.seed_window_counts(st, own_keys={"k2"}) == {}

    def test_claim_refused_for_healthy_committed_key(self, tmp_path):
        """A peer's commit landing between a drain scan and the claim
        append must not let a second worker re-run the job (the
        duplicate-commit race): try_claim re-checks terminal state on
        its own fresh replay."""
        j = _journal(tmp_path)
        p = tmp_path / "out.fasta"
        p.write_text(">r\nACGT\n")
        j.append("committed", key="k", job="x",
                 outputs={str(p): sjournal.file_fingerprint(str(p))})
        c = _coord(j, "wb")
        assert not c.try_claim("k", "job")
        # ... but a commit whose outputs DRIFTED is claimable (the
        # re-run restores them — the serial restart contract)
        os.unlink(p)
        assert c.try_claim("k", "job")

    def test_stale_failures_are_reclaimable_fresh_ones_not(self,
                                                           tmp_path):
        j = _journal(tmp_path)
        j.append("failed", key="k", job="x", error="old crash")
        c = _coord(j, "wb")
        assert not c.try_claim("k", "job")     # fresh failure: terminal
        assert c.try_claim("k", "job",
                           reclaim_stale_failed=True)  # restart retry

    def test_woken_zombie_never_journals_its_failure(self, tmp_path):
        """A worker whose lease was stolen mid-run must journal
        NOTHING for the job — even a failure: a 'failed' append would
        pop the thief's live claim and wreck its commit."""
        from sam2consensus_tpu.serve import JobSpec, ServeRunner

        path = _sim(tmp_path, "z.sam", 73, prefix="zz_")
        out = str(tmp_path / "out")
        os.makedirs(out)
        r = ServeRunner(prewarm="off", persistent_cache=False,
                        journal_dir=str(tmp_path / "j"),
                        worker_id="w0", lease_ttl=0.2)
        thief_events = []

        def hijacked_execute(*a, **k):
            # model the zombie: the run outlives the TTL (no renewals
            # fire inside this stub), a thief reaps + re-claims, and
            # then OUR run fails
            time.sleep(0.3)
            jj = sjournal.JobJournal(r.journal.root,
                                     checkpoint_every=0)
            st = jj.read_state()
            (key, cur), = st.claims.items()
            thief_events.append(key)
            jj.append("lease_expired", key=key, worker="w0",
                      reaper="thief")
            jj.append("claimed", key=key, job="stolen", worker="thief",
                      expires_unix=time.time() + 60)
            raise RuntimeError("boom after steal")

        r._execute = hijacked_execute
        try:
            res = r.submit_jobs([JobSpec(
                filename=path,
                config=RunConfig(backend="jax", outfolder=out,
                                 prefix="pz"))])[0]
            assert not res.ok
            assert "lease lost" in res.error
            st = r.journal.read_state()
            key = thief_events[0]
            # no failed event polluted the journal; the thief's claim
            # is intact and it owns the lifecycle
            assert st.failed == {}
            assert st.claims[key]["worker"] == "thief"
            assert r.registry.value("fleet/lease_lost") == 1
        finally:
            r.close()

    def test_admission_seed_window_charges_quota(self):
        from sam2consensus_tpu.serve.admission import (
            REASON_TENANT_QUOTA, AdmissionController)

        adm = AdmissionController(tenant_quota=2)
        adm.open_window()
        adm.seed_window({"tb": 2})
        dec = adm.admit("tb")
        assert not dec.admitted and dec.reason == REASON_TENANT_QUOTA
        assert adm.admit("other").admitted


# =========================================================================
# concurrent journal writers (satellite: hammer test)
# =========================================================================
_HAMMER = """
import sys
from sam2consensus_tpu.serve.journal import JobJournal
j = JobJournal(sys.argv[1], checkpoint_every=0)
tag, n = sys.argv[2], int(sys.argv[3])
for i in range(n):
    j.append("submitted", key=f"{tag}-{i}", job=f"{tag}{i}")
"""


def _hammer(jdir, writers, per_writer):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, "-c", _HAMMER, jdir, f"w{k}",
         str(per_writer)], env=env, stderr=subprocess.PIPE)
        for k in range(writers)]
    for p in procs:
        _, err = p.communicate(timeout=120)
        assert p.returncode == 0, err.decode()


class TestConcurrentWriters:
    def test_two_writers_never_tear_or_misorder(self, tmp_path):
        jdir = str(tmp_path / "j")
        _hammer(jdir, writers=2, per_writer=40)
        j = sjournal.JobJournal(jdir, checkpoint_every=0)
        evs = j.events()
        assert len(evs) == 80
        assert not any(e["ev"] == "_corrupt" for e in evs)
        seqs = [e["seq"] for e in evs]
        # dense, unique, ordered: the O_EXCL link allocation worked
        assert seqs == sorted(seqs)
        assert len(set(seqs)) == 80
        assert seqs == list(range(seqs[0], seqs[0] + 80))
        st = j.replay(full=True)
        assert len(st.submitted) == 80

    @pytest.mark.slow
    def test_three_writers_hammer_full(self, tmp_path):
        jdir = str(tmp_path / "j")
        _hammer(jdir, writers=3, per_writer=300)
        j = sjournal.JobJournal(jdir, checkpoint_every=0)
        evs = j.events()
        assert len(evs) == 900
        seqs = [e["seq"] for e in evs]
        assert len(set(seqs)) == 900 and seqs == sorted(seqs)


# =========================================================================
# checkpoint / compaction (satellite: replay cursor)
# =========================================================================
def _state_tuple(st):
    return (st.committed, st.failed, st.inflight, st.commit_counts,
            st.submitted, st.claims, st.tenants)


class TestCheckpointCompaction:
    def _busy_journal(self, tmp_path, every=16):
        j = sjournal.JobJournal(str(tmp_path / "j"),
                                checkpoint_every=every)
        now = time.time()
        for i in range(40):
            key = f"k{i}"
            j.append("submitted", key=key, job=f"job{i}", tenant="t")
            j.append("claimed", key=key, worker="wa",
                     expires_unix=now + 600)
            j.append("started", key=key, job=f"job{i}", worker="wa",
                     tenant="t")
            if i % 3 == 0:
                j.append("failed", key=key, job=f"job{i}", error="x")
            elif i % 3 == 1:
                j.append("committed", key=key, job=f"job{i}",
                         outputs={}, elapsed_sec=0.1, worker="wa")
            # i % 3 == 2 stays in flight with a live claim
        return j

    def test_compacted_replay_equals_full_replay(self, tmp_path):
        j = self._busy_journal(tmp_path)
        base, loaded = j._latest_checkpoint()
        assert base > 0 and loaded is not None   # checkpoints exist
        fast = j.replay()
        full = j.replay(full=True)
        assert _state_tuple(fast) == _state_tuple(full)
        assert fast.last_seq == full.last_seq
        assert j.audit() == j.audit(full=True)

    def test_replay_is_o_tail_after_prune(self, tmp_path):
        j = self._busy_journal(tmp_path)
        before = j.replay()
        n_segs = len(j._segments())
        removed = j.prune()
        assert removed > 0
        assert len(j._segments()) < n_segs
        after = j.replay()
        assert _state_tuple(before) == _state_tuple(after)
        # appends keep working past a prune (seq continues, not reused)
        seq = j.append("submitted", key="fresh", job="fresh")
        assert seq == before.last_seq + 1
        assert "fresh" in j.replay().submitted

    def test_corrupt_checkpoint_falls_back(self, tmp_path):
        j = self._busy_journal(tmp_path)
        full = j.replay(full=True)
        ckpts = j._listing("checkpoint")
        with open(ckpts[-1][1], "w") as fh:
            fh.write("{torn")
        again = j.replay()                 # older ckpt or genesis
        assert _state_tuple(again) == _state_tuple(full)


# =========================================================================
# verify_outputs fast path (satellite)
# =========================================================================
class TestVerifyOutputs:
    def _committed(self, p):
        return {"outputs": {str(p): sjournal.file_fingerprint(str(p))}}

    def test_untouched_passes_without_rehash(self, tmp_path,
                                             monkeypatch):
        p = tmp_path / "out.fasta"
        p.write_text(">r\nACGT\n")
        rec = self._committed(p)
        j = _journal(tmp_path)
        calls = []
        orig = sjournal.file_sha256
        monkeypatch.setattr(sjournal, "file_sha256",
                            lambda q: calls.append(q) or orig(q))
        assert j.verify_outputs(rec)
        assert calls == []                 # stat fast path, no hash

    def test_touched_but_identical_still_passes(self, tmp_path):
        p = tmp_path / "out.fasta"
        p.write_text(">r\nACGT\n")
        rec = self._committed(p)
        time.sleep(0.01)
        os.utime(p)                        # mtime drifts, bytes same
        j = _journal(tmp_path)
        assert j.verify_outputs(rec)       # re-hash path, passes

    def test_corrupted_same_size_fails(self, tmp_path):
        p = tmp_path / "out.fasta"
        p.write_text(">r\nACGT\n")
        rec = self._committed(p)
        time.sleep(0.01)
        p.write_text(">r\nTTTT\n")         # same size, new bytes
        j = _journal(tmp_path)
        assert not j.verify_outputs(rec)

    def test_full_mode_catches_mtime_reset_corruption(self, tmp_path):
        """An adversarially reset mtime fools the stat fast path by
        design — ``--verify-outputs full`` is the escape hatch."""
        p = tmp_path / "out.fasta"
        p.write_text(">r\nACGT\n")
        rec = self._committed(p)
        fp = rec["outputs"][str(p)]
        p.write_text(">r\nTTTT\n")
        os.utime(p, (fp["mtime"], fp["mtime"]))
        j = _journal(tmp_path)
        assert j.verify_outputs(rec)           # fooled (documented)
        assert not j.verify_outputs(rec, mode="full")

    def test_size_change_and_missing_fail_fast(self, tmp_path):
        p = tmp_path / "out.fasta"
        p.write_text(">r\nACGT\n")
        rec = self._committed(p)
        p.write_text(">r\nACGTACGT\n")
        j = _journal(tmp_path)
        assert not j.verify_outputs(rec)
        os.unlink(p)
        assert not j.verify_outputs(rec)

    def test_legacy_string_fingerprints_still_verify(self, tmp_path):
        p = tmp_path / "out.fasta"
        p.write_text(">r\nACGT\n")
        rec = {"outputs": {str(p): sjournal.file_sha256(str(p))}}
        j = _journal(tmp_path)
        assert j.verify_outputs(rec)
        p.write_text(">r\nTTTT\n")
        assert not j.verify_outputs(rec)


# =========================================================================
# runner integration (in-process, single worker)
# =========================================================================
class TestFleetRunner:
    def test_single_worker_fleet_end_to_end(self, tmp_path):
        from sam2consensus_tpu.observability.telemetry import \
            lint_openmetrics
        from sam2consensus_tpu.serve import JobSpec, ServeRunner

        paths = [_sim(tmp_path, f"j{k}.sam", 60 + k,
                      prefix=f"fr{k}_") for k in range(2)]
        out = str(tmp_path / "out")
        os.makedirs(out)
        r = ServeRunner(prewarm="off", persistent_cache=False,
                        journal_dir=str(tmp_path / "j"),
                        worker_id="w0", lease_ttl=30.0)
        try:
            res = r.submit_jobs([
                JobSpec(filename=p,
                        config=RunConfig(backend="jax", outfolder=out,
                                         prefix=f"p{k}"),
                        tenant="ta")
                for k, p in enumerate(paths)])
            assert all(x.ok for x in res)
            assert all(x.worker == "w0" for x in res)
            assert all(x.output_paths for x in res)
            # manifest records the committing worker (satellite)
            assert res[0].manifest["serve"]["worker"] == "w0"
            audit = r.journal.audit()
            assert not audit["lost"] and not audit["duplicated"]
            # health snapshot: worker identity + lease section
            snap = r.health_snapshot()
            assert snap["worker_id"] == "w0"
            assert snap["lease"]["claims"] == 2
            assert snap["lease"]["held"] == {}
            # exposition: worker-labeled, lint-clean
            tel = r.render_telemetry()
            assert lint_openmetrics(tel) == []
            samples = [ln for ln in tel.splitlines()
                       if ln and not ln.startswith("#")]
            assert samples
            assert all('worker="w0"' in ln for ln in samples)
            assert any("s2c_fleet_claims_total" in ln
                       for ln in samples)
        finally:
            r.close()

    def test_worker_id_requires_journal(self):
        from sam2consensus_tpu.serve import ServeRunner

        with pytest.raises(ValueError, match="requires --journal"):
            ServeRunner(prewarm="off", persistent_cache=False,
                        worker_id="w0")

    def test_worker_id_rejects_batch(self, tmp_path):
        from sam2consensus_tpu.serve import ServeRunner

        with pytest.raises(ValueError, match="--batch"):
            ServeRunner(prewarm="off", persistent_cache=False,
                        journal_dir=str(tmp_path / "j"),
                        worker_id="w0", batch="4")

    def test_worker_id_rejects_count_cache(self, tmp_path):
        """--count-cache on a fleet worker would be a silent no-op
        (incremental jobs are rejected on journaled servers): refuse
        it up front instead."""
        from sam2consensus_tpu.serve import ServeRunner

        with pytest.raises(ValueError, match="--count-cache"):
            ServeRunner(prewarm="off", persistent_cache=False,
                        journal_dir=str(tmp_path / "j"),
                        worker_id="w0", count_cache="64M")

    def test_drifted_commit_is_reclaimed_and_rerun(self, tmp_path):
        """A committed job whose outputs no longer verify must be
        RE-RUN by the fleet drain (the serial restart path's
        contract), not reported as completed-elsewhere."""
        from sam2consensus_tpu.serve import JobSpec, ServeRunner

        path = _sim(tmp_path, "d.sam", 71, prefix="dr_")
        out = str(tmp_path / "out")
        os.makedirs(out)
        spec = JobSpec(filename=path,
                       config=RunConfig(backend="jax", outfolder=out,
                                        prefix="pd"))
        r1 = ServeRunner(prewarm="off", persistent_cache=False,
                         journal_dir=str(tmp_path / "j"),
                         worker_id="w0", lease_ttl=30.0)
        try:
            first = r1.submit_jobs([spec])[0]
            assert first.ok and first.output_paths
        finally:
            r1.close()
        target = first.output_paths[0]
        os.unlink(target)                   # corrupt the commit
        r2 = ServeRunner(prewarm="off", persistent_cache=False,
                         journal_dir=str(tmp_path / "j"),
                         worker_id="w0", lease_ttl=30.0)
        try:
            redo = r2.submit_jobs([spec])[0]
            assert redo.ok
            assert not redo.resumed          # ran, not skipped
            assert redo.worker == "w0"
            assert os.path.exists(target)    # outputs restored
        finally:
            r2.close()

    def test_drain_stall_backstop_raises(self, tmp_path):
        """Dead journal appends (disk full) must fail the drain
        loudly, not spin forever."""
        j = _journal(tmp_path)
        coord = FleetCoordinator(j, "w0", 5.0, MetricsRegistry())
        coord.drain_stall_budget = 0.4

        class _StubRunner:
            journal = j
            verify_mode = "fast"
            slo = {}

            class admission:
                slo_burn_by_tenant = {}

            def telemetry_tick(self):
                pass

        def broken_append(*a, **k):
            raise OSError("disk full")

        j.append = broken_append
        plan = [{"action": "run", "key": "k0", "job_id": "j0"}]
        with pytest.raises(RuntimeError, match="stalled"):
            coord.drain(_StubRunner(), plan, 0.0, j.replay(), None)

    def test_fleet_journal_refuses_workerless_restart(self, tmp_path):
        """Commits on ever-claimed keys are lease-fenced, so a
        worker-less server could never commit them — refuse loudly."""
        from sam2consensus_tpu.serve import JobSpec, ServeRunner

        jdir = str(tmp_path / "j")
        sjournal.JobJournal(jdir, checkpoint_every=0).append(
            "claimed", key="k", job="x", worker="dead",
            expires_unix=time.time() - 5)
        path = _sim(tmp_path, "w.sam", 77, n_reads=200, prefix="wl_")
        r = ServeRunner(prewarm="off", persistent_cache=False,
                        journal_dir=jdir)
        try:
            with pytest.raises(ValueError, match="--worker-id"):
                r.submit_jobs([JobSpec(
                    filename=path,
                    config=RunConfig(backend="jax",
                                     outfolder=str(tmp_path)))])
        finally:
            r.close()

    def test_bad_verify_mode_rejected(self):
        from sam2consensus_tpu.serve import ServeRunner

        with pytest.raises(ValueError, match="verify_outputs"):
            ServeRunner(prewarm="off", persistent_cache=False,
                        verify_outputs="sometimes")

    def test_serve_cli_validations(self, tmp_path, capsys):
        from sam2consensus_tpu.cli import serve_main

        with pytest.raises(SystemExit,
                           match="--worker-id requires --journal"):
            serve_main(["-i", "x.sam", "--worker-id", "w0"])
        with pytest.raises(SystemExit, match="--batch"):
            serve_main(["-i", "x.sam", "--journal",
                        str(tmp_path / "j"), "--worker-id", "w0",
                        "--batch", "4"])
        with pytest.raises(SystemExit, match="--lease-ttl"):
            serve_main(["-i", "x.sam", "--journal",
                        str(tmp_path / "j"), "--worker-id", "w0",
                        "--lease-ttl", "0"])
        with pytest.raises(SystemExit, match="--count-cache"):
            serve_main(["-i", "x.sam", "--journal",
                        str(tmp_path / "j"), "--worker-id", "w0",
                        "--count-cache", "64M"])


# =========================================================================
# 2-worker subprocess smoke (tier-1 fast; the full rotating-kill soak
# is the slow test below + the committed campaign artifact)
# =========================================================================
def _serve_cmd(inputs, outdir, jdir, worker, extra=()):
    cmd = [sys.executable, "-m", "sam2consensus_tpu.cli", "serve"]
    for p in inputs:
        cmd += ["-i", p]
    cmd += ["-o", outdir, "--journal", jdir, "--worker-id", worker,
            "--lease-ttl", "10", "--pileup", "scatter", "--quiet",
            *extra]
    return cmd


def _sha_dir(d):
    import hashlib

    return {n: hashlib.sha256(
        open(os.path.join(d, n), "rb").read()).hexdigest()
        for n in sorted(os.listdir(d))}


class TestFleetSmoke:
    def test_two_workers_drain_byte_identical_to_serial(self, tmp_path):
        inputs = [_sim(tmp_path, f"s{k}.sam", 80 + k, n_reads=600,
                       prefix=f"sm{k}_") for k in range(3)]
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH",
                                                        "")
        env["JAX_PLATFORMS"] = "cpu"
        env["S2C_JIT_CACHE"] = str(tmp_path / "_jit_cache")
        out1, j1 = str(tmp_path / "o1"), str(tmp_path / "jj1")
        r = subprocess.run(_serve_cmd(inputs, out1, j1, "solo"),
                           env=env, capture_output=True, timeout=300)
        assert r.returncode == 0, r.stderr.decode()
        out2, j2 = str(tmp_path / "o2"), str(tmp_path / "jj2")
        procs = [subprocess.Popen(
            _serve_cmd(inputs, out2, j2, w), env=env,
            stderr=subprocess.PIPE) for w in ("fw0", "fw1")]
        for p in procs:
            _, err = p.communicate(timeout=300)
            assert p.returncode == 0, err.decode()
        assert _sha_dir(out1) == _sha_dir(out2)
        audit = sjournal.JobJournal(j2).audit()
        assert audit["lost"] == [] and audit["duplicated"] == []
        assert len(audit["commit_counts"]) == 3
        evs = sjournal.JobJournal(j2).events()
        claimers = {e.get("worker") for e in evs
                    if e.get("ev") == "claimed"}
        assert claimers <= {"fw0", "fw1"} and claimers

    @pytest.mark.slow
    def test_rotating_kill_soak(self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import fleet_soak

        out = str(tmp_path / "soak.jsonl")
        rc = fleet_soak.main([
            "--cycles", "3", "--jobs", "3", "--reads", "6000",
            "--contig-len", "4000", "--lease-ttl", "2.0",
            "--skip-speedup", "--out", out,
            "--workdir", str(tmp_path / "wk")])
        assert rc == 0
        rows = [json.loads(ln) for ln in open(out) if ln.strip()]
        summary = rows[-1]
        assert summary["failures"] == 0
        assert summary["identical_all"] is True
        assert summary["lost_total"] == 0
        assert summary["duplicated_total"] == 0
        steals = [r["steal_sec"] for r in rows
                  if r.get("steal_sec") is not None]
        assert steals, "no chaos signal landed"
        assert all(s <= summary["steal_bound_sec"] for s in steals)


# =========================================================================
# exposition worker labels + s2c_top --fleet
# =========================================================================
class TestFleetTelemetry:
    def test_worker_label_round_trips_and_lints(self):
        from sam2consensus_tpu.observability.telemetry import (
            lint_openmetrics, parse_openmetrics, render_openmetrics)

        reg = MetricsRegistry()
        reg.add("fleet/claims", 3)
        reg.add("phase/decode_sec", 1.5)
        reg.observe("slo/ta/e2e", 0.7)
        text = render_openmetrics(reg.snapshot(), worker="w3")
        assert lint_openmetrics(text) == []
        samples = parse_openmetrics(text)
        assert samples
        assert all(s["labels"].get("worker") == "w3" for s in samples)
        # two workers' scrapes merge without collisions
        other = parse_openmetrics(
            render_openmetrics(reg.snapshot(), worker="w4"))
        keys = {(s["name"], tuple(sorted(s["labels"].items())))
                for s in samples + other}
        assert len(keys) == len(samples) + len(other)

    def _healths(self):
        h0 = {"worker_id": "w0", "uptime_sec": 30.0, "queue_depth": 1,
              "in_flight": "job3:a.sam", "in_flight_sec": 4.0,
              "last_heartbeat_age_sec": 0.2,
              "jobs": {"run": 3, "failed": 0},
              "lease": {"held": {"k1": {"expires_in_sec": 8.0,
                                        "last_renew_age_sec": 1.0}},
                        "reaped": 1, "steals": 1, "lease_lost": 0,
                        "claims": 4, "claim_lost": 1},
              "slo": {"burn_by_tenant": {"ta": 2}},
              "journal": {"root": "/j", "last_seq": 17}}
        h1 = {"worker_id": "w1", "uptime_sec": 29.0, "queue_depth": 0,
              "in_flight": None, "in_flight_sec": None,
              "last_heartbeat_age_sec": 0.4,
              "jobs": {"run": 2, "failed": 1},
              "lease": {"held": {}, "reaped": 0, "steals": 0,
                        "lease_lost": 0, "claims": 2,
                        "claim_lost": 2},
              "slo": {"burn_by_tenant": {"ta": 1}},
              "journal": {"root": "/j", "last_seq": 17}}
        return [("h0.json", h0), ("h1.json", h1)]

    def test_s2c_top_fleet_frame(self):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import s2c_top

        samples = [
            {"name": "s2c_slo_phase_seconds",
             "labels": {"tenant": "ta", "phase": "e2e",
                        "quantile": "0.99", "worker": "w0"},
             "value": 1.25},
            {"name": "s2c_slo_phase_seconds",
             "labels": {"tenant": "ta", "phase": "e2e",
                        "quantile": "0.99", "worker": "w1"},
             "value": 2.5},
            {"name": "s2c_slo_violations_total",
             "labels": {"tenant": "ta", "phase": "e2e",
                        "worker": "w0"}, "value": 2},
            {"name": "s2c_slo_violations_total",
             "labels": {"tenant": "ta", "phase": "e2e",
                        "worker": "w1"}, "value": 1},
        ]
        frame = s2c_top.render_fleet(self._healths(), samples)
        text = "\n".join(frame)
        assert "2 worker(s) (2 reporting)" in text
        assert "jobs 5 (1 failed)" in text
        assert "leases held 1, reaped 1, stolen 1" in text
        w0row = next(ln for ln in frame if ln.startswith("w0"))
        assert "job3:a.sam" in w0row
        assert any(ln.startswith("w1") for ln in frame)
        assert "slo burn by tenant (all workers): {'ta': 3}" in text
        assert "w0=1.250s" in text and "w1=2.500s" in text
        assert "journal:" in text

    def test_s2c_top_fleet_waits_without_snapshots(self):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import s2c_top

        frame = s2c_top.render_fleet([("h.json", None)], None)
        assert "waiting" in frame[0]

    def test_s2c_top_single_frame_shows_lease_line(self):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import s2c_top

        frame = s2c_top.render(self._healths()[0][1], None)
        assert any("worker: w0" in ln and "steals 1" in ln
                   for ln in frame)


# =========================================================================
# claim evidence: committed artifact + check_perf_claims integration
# =========================================================================
class TestFleetArtifact:
    ARTIFACT = os.path.join(REPO, "campaign",
                            "fleet_soak_r06_cpufallback.jsonl")

    def test_committed_artifact_invariants(self):
        rows = [json.loads(ln) for ln in open(self.ARTIFACT)
                if ln.strip()]
        summary = [r for r in rows if r.get("mode") == "summary"][-1]
        cycles = [r for r in rows if isinstance(r.get("cycle"), int)]
        assert summary["identical_all"] is True
        assert summary["lost_total"] == 0
        assert summary["duplicated_total"] == 0
        assert summary["failures"] == 0
        assert summary["signaled_cycles"] >= 2    # chaos landed
        assert {"kill", "wedge", "fault"} <= {r["mode"]
                                              for r in cycles}
        # the 2x-TTL takeover bound held on every signaled cycle
        assert summary["max_steal_sec"] is not None
        assert summary["max_steal_sec"] <= summary["steal_bound_sec"]
        # the speedup leg is present and honest about its host
        assert summary["host_cores"] >= 1
        assert summary["drain_speedup"] is not None

    def test_check_perf_claims_lints_fleet_artifacts(self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import check_perf_claims

        assert check_perf_claims.lint_fleet_soak_artifact(
            self.ARTIFACT) == []
        bad = tmp_path / "fleet_soak_bad.jsonl"
        bad.write_text(json.dumps(
            {"mode": "summary", "lost_total": 1,
             "duplicated_total": 0, "identical_all": True,
             "failures": 0}) + "\n")
        errs = check_perf_claims.lint_fleet_soak_artifact(str(bad))
        assert any("lost_total" in e for e in errs)
        none = tmp_path / "fleet_soak_empty.jsonl"
        none.write_text("")
        assert check_perf_claims.lint_fleet_soak_artifact(
            str(none)) == ["no summary row"]
