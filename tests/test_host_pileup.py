"""Host-counts pileup strategy: native accumulate, wire narrowing, parity.

The host path (ops/pileup.py HostPileupAccumulator) accumulates the count
tensor in native code and ships it to the device once, dtype-narrowed —
the least-wire strategy on deep/small-genome workloads (see its docstring
for the tunnel measurements).  These tests pin:

* count parity: native C++ slab walk == numpy fallback == device scatter;
* dtype narrowing thresholds (uint8 / uint16 / int32) and vote parity
  across them;
* full-backend byte identity vs the CPU oracle with --pileup host,
  including checkpoints/resume composition.
"""

import io
import os
import tempfile

import pytest

import numpy as np

from sam2consensus_tpu.backends.cpu import CpuBackend
from sam2consensus_tpu.backends.jax_backend import JaxBackend
from sam2consensus_tpu.config import RunConfig
from sam2consensus_tpu.encoder.events import GenomeLayout, ReadEncoder
from sam2consensus_tpu.io.fasta import render_file
from sam2consensus_tpu.io.sam import iter_records, read_header
from sam2consensus_tpu.ops.pileup import (HOST_PILEUP_MAX_LEN,
                                          HostPileupAccumulator,
                                          PileupAccumulator)
from sam2consensus_tpu.utils.simulate import SimSpec, simulate


def _encode_all(text):
    handle = io.StringIO(text)
    contigs, _n, first = read_header(handle)
    layout = GenomeLayout(contigs)
    enc = ReadEncoder(layout)
    chunks = list(enc.encode_segments(iter_records(handle, first),
                                      chunk_reads=64))
    return layout, chunks


def test_host_counts_equal_device_scatter():
    text = simulate(SimSpec(n_contigs=4, contig_len=250, n_reads=700,
                            read_len=50, ins_read_rate=0.1,
                            del_read_rate=0.1, seed=41))
    layout, chunks = _encode_all(text)

    dev = PileupAccumulator(layout.total_len, strategy="scatter")
    host = HostPileupAccumulator(layout.total_len)
    for c in chunks:
        dev.add(c)
        host.add(c)
    np.testing.assert_array_equal(host.counts_host(),
                                  np.asarray(dev.counts))


def test_native_accumulate_equals_numpy_fallback():
    from sam2consensus_tpu import native

    if native.load() is None:
        import pytest

        pytest.skip("native decoder unavailable")
    text = simulate(SimSpec(n_contigs=3, contig_len=200, n_reads=400,
                            read_len=40, seed=42))
    layout, chunks = _encode_all(text)
    a = HostPileupAccumulator(layout.total_len)
    b = HostPileupAccumulator(layout.total_len)
    b._lib = None                       # force the numpy fallback
    for c in chunks:
        a.add(c)
        b.add(c)
    np.testing.assert_array_equal(a.counts_host(), b.counts_host())


def test_wire_dtype_narrowing_and_vote_parity():
    import jax.numpy as jnp

    from sam2consensus_tpu.ops.cutoff import encode_thresholds
    from sam2consensus_tpu.ops.vote import vote_positions

    thr = jnp.asarray(encode_thresholds([0.25, 0.75]))
    rng = np.random.default_rng(5)
    for peak, want_dtype in ((200, "uint8"), (60000, "uint16"),
                             (70000, "int32")):
        acc = HostPileupAccumulator(64)
        acc._counts[:] = rng.integers(0, 7, (64, 6)).astype(np.int32)
        acc._counts[3, 2] = peak
        dev = acc.counts
        assert acc.strategy_used["host_wire_dtype"] == want_dtype
        syms_narrow, cov_narrow = vote_positions(dev, thr, 1)
        syms_full, cov_full = vote_positions(
            jnp.asarray(acc.counts_host()), thr, 1)
        np.testing.assert_array_equal(np.asarray(syms_narrow),
                                      np.asarray(syms_full))
        np.testing.assert_array_equal(np.asarray(cov_narrow),
                                      np.asarray(cov_full))


def _run(text, backend, cfg):
    handle = io.StringIO(text)
    contigs, _n, first = read_header(handle)
    res = backend.run(contigs, iter_records(handle, first), cfg)
    return {n: render_file(r, 0) for n, r in res.fastas.items()}, res.stats


def test_backend_host_pileup_byte_identical(monkeypatch):
    text = simulate(SimSpec(n_contigs=5, contig_len=180, n_reads=600,
                            read_len=40, ins_read_rate=0.15,
                            del_read_rate=0.15, seed=43))
    cfg = RunConfig(prefix="t", thresholds=[0.25, 0.5, 0.75], shards=1)
    out_cpu, _ = _run(text, CpuBackend(), cfg)
    cfg_h = RunConfig(prefix="t", thresholds=[0.25, 0.5, 0.75], shards=1,
                      pileup="host")
    out_host, st = _run(text, JaxBackend(), cfg_h)
    assert out_host == out_cpu
    assert st.extra["pileup"]["host"] > 0

    # wire-dtype narrowing: only observable on the fused wire path —
    # the native link-free tail ships nothing, so pin the tail to the
    # default device for this check
    monkeypatch.setenv("S2C_TAIL_DEVICE", "default")
    out_wire, st2 = _run(text, JaxBackend(), cfg_h)
    assert out_wire == out_cpu
    assert "host_wire_dtype" in st2.extra["pileup"]


def test_auto_picks_host_below_threshold():
    text = simulate(SimSpec(n_contigs=2, contig_len=150, n_reads=200,
                            read_len=30, seed=44))
    cfg = RunConfig(prefix="t", thresholds=[0.25], shards=1, pileup="auto")
    _out, st = _run(text, JaxBackend(), cfg)
    assert "host" in st.extra["pileup"]
    assert HOST_PILEUP_MAX_LEN >= 300          # policy sanity


@pytest.mark.parametrize("direct_min", [None, "1"])
def test_host_pileup_checkpoint_resume(monkeypatch, direct_min):
    """Kill mid-run, resume with --pileup host: same bytes as one-shot.
    Parametrized over both fused-counting modes (direct_min="1" forces
    the direct-int32 path; checkpoints there snapshot the pileup with no
    shadow merge pending)."""
    from sam2consensus_tpu.io.sam import ReadStream, opener

    if direct_min is None:
        monkeypatch.delenv("S2C_FUSED_DIRECT_MIN_LEN", raising=False)
    else:
        monkeypatch.setenv("S2C_FUSED_DIRECT_MIN_LEN", direct_min)
    text = simulate(SimSpec(n_contigs=3, contig_len=120, n_reads=300,
                            read_len=30, seed=45))
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "in.sam")
        with open(path, "w") as fh:
            fh.write(text)
        ckdir = os.path.join(tmp, "ck")

        def run_stream(cfg):
            handle = opener(path, binary=True)
            contigs, _n, first = read_header(handle)
            res = JaxBackend().run(contigs, ReadStream(handle, first), cfg)
            handle.close()
            return {n: render_file(r, 0) for n, r in res.fastas.items()}

        base = RunConfig(prefix="t", thresholds=[0.25], shards=1,
                         pileup="host")
        want = run_stream(base)

        cfg_ck = RunConfig(prefix="t", thresholds=[0.25], shards=1,
                           pileup="host", checkpoint_dir=ckdir,
                           checkpoint_every=100)
        got = run_stream(cfg_ck)               # writes + clears checkpoints
        assert got == want


def test_sparse_output_tail_byte_identical(monkeypatch):
    """Sparse-coverage genome routes through the sparse-output tail
    (emit bitmask + compacted chars) and stays byte-identical, with and
    without insertions.  The CI platform is link-free (everything runs
    on the local cpu backend), where the auto gate correctly refuses
    sparse — S2C_TAIL_ENCODING=sparse exercises the path anyway."""
    from sam2consensus_tpu.utils.simulate import sam_text

    monkeypatch.setenv("S2C_TAIL_ENCODING", "sparse")
    # big genome, few reads -> aligned_bases << L keeps the cap small
    text = simulate(SimSpec(n_contigs=2, contig_len=200_000, n_reads=300,
                            read_len=60, ins_read_rate=0.3,
                            del_read_rate=0.2, seed=46))
    cfg = RunConfig(prefix="t", thresholds=[0.25, 0.75], shards=1)
    out_cpu, _ = _run(text, CpuBackend(), cfg)
    out_jax, st = _run(text, JaxBackend(), cfg)
    assert out_jax == out_cpu
    # the fetch must actually have been sparse for this shape
    assert st.extra["d2h_bytes"] < 2 * 200_000 * 2, st.extra

    # no-insertion flavor
    text2 = sam_text([("big", 150_000)],
                     [("big", 5, "30M", "ACGTACGTACGTACGTACGTACGTACGTAC"),
                      ("big", 120_000, "30M",
                       "ACGTACGTACGTACGTACGTACGTACGTAC")])
    out_cpu2, _ = _run(text2, CpuBackend(), cfg)
    out_jax2, st2 = _run(text2, JaxBackend(), cfg)
    assert out_jax2 == out_cpu2


def test_sparse_output_auto_gate_link_free(monkeypatch):
    """On a link-free platform (default backend == cpu) the auto gate
    refuses sparse even for shapes where a tunneled link would pick it —
    the 'saved' dense fetch would be a local memcpy while the compaction
    scatter + host re-expansion are real costs."""
    monkeypatch.delenv("S2C_TAIL_ENCODING", raising=False)
    text = simulate(SimSpec(n_contigs=2, contig_len=200_000, n_reads=300,
                            read_len=60, seed=46))
    cfg = RunConfig(prefix="t", thresholds=[0.25, 0.75], shards=1)
    _out, st = _run(text, JaxBackend(), cfg)
    # dense fetch: ~2 thresholds x ~400k positions (contig jitter), far
    # above what the sparse encoding would ship for 300 short reads
    assert st.extra["d2h_bytes"] == 0 \
        or st.extra["d2h_bytes"] >= 2 * 350_000, st.extra


def test_packed5_output_byte_identical(monkeypatch):
    """The 5-bit packed output encoding (nibble plane + high-bit plane,
    constants.SYM32_ASCII) decodes byte-identically — including
    lowercase/'B'/'n' calls, which live on the high plane and take the
    per-position fixup path in _expand_packed5."""
    monkeypatch.setenv("S2C_TAIL_ENCODING", "packed5")
    # high indel rates force gap/nucleotide ties -> lowercase IUPAC calls
    text = simulate(SimSpec(n_contigs=3, contig_len=5_000, n_reads=4_000,
                            read_len=60, ins_read_rate=0.3,
                            del_read_rate=0.35, seed=48))
    for thr in ([0.25], [0.25, 0.5, 0.75]):
        cfg = RunConfig(prefix="t", thresholds=thr, shards=1)
        out_cpu, _ = _run(text, CpuBackend(), cfg)
        out_jax, st = _run(text, JaxBackend(), cfg)
        assert out_jax == out_cpu
    # the output must actually be lowercase-bearing (high-plane symbols)
    # for the fixup branch to have been exercised
    assert any(ch.islower()
               for f in out_cpu.values()
               for line in f.split("\n") if not line.startswith(">")
               for ch in line), "fixture produced no lowercase calls"


def test_tail_routing_matrix(monkeypatch):
    """The placement gates must agree with each other: a condition that
    disables the native cpu tail (explicit pallas kernel, forced wire
    encoding) must also stop the host-pileup gate from widening on the
    native tail's economics — otherwise counts accumulate host-side and
    then ship over the link (round-3 review finding)."""
    from sam2consensus_tpu.backends.jax_backend import _native_tail_possible
    from sam2consensus_tpu.ops.pileup import host_pileup_max_len

    monkeypatch.delenv("S2C_TAIL_ENCODING", raising=False)
    monkeypatch.delenv("S2C_TAIL_DEVICE", raising=False)
    cfg_auto = RunConfig(prefix="t", thresholds=[0.25], shards=1)
    cfg_pallas = RunConfig(prefix="t", thresholds=[0.25], shards=1,
                           ins_kernel="pallas")
    from sam2consensus_tpu import native
    if native.load() is None:
        assert not _native_tail_possible(cfg_auto)
        return
    assert _native_tail_possible(cfg_auto)
    wide = host_pileup_max_len(_native_tail_possible(cfg_auto))
    assert wide == (1 << 23)
    # explicit pallas keeps the device tail -> narrow gate
    assert not _native_tail_possible(cfg_pallas)
    assert host_pileup_max_len(
        _native_tail_possible(cfg_pallas)) == (1 << 21)
    # forced wire encoding runs the fused XLA path -> narrow gate
    monkeypatch.setenv("S2C_TAIL_ENCODING", "packed5")
    assert not _native_tail_possible(cfg_auto)
    monkeypatch.delenv("S2C_TAIL_ENCODING")
    # forced device tail -> narrow gate
    monkeypatch.setenv("S2C_TAIL_DEVICE", "default")
    assert not _native_tail_possible(cfg_auto)


def test_host_gate_link_aware(monkeypatch):
    """A tunnel-class modeled link removes the host-pileup genome bound
    entirely (the device path's wire floor loses at every L); a
    PCIe-class link keeps the narrow 2^23 bound (round-4 wide-genome
    mis-route: chip-routed 40 Mbp ran 3.5 s vs the host's 1.2 s on the
    ~8-40 MB/s tunnel)."""
    from sam2consensus_tpu.ops.pileup import host_pileup_max_len

    monkeypatch.delenv("S2C_HOST_PILEUP_MAX_LEN", raising=False)
    monkeypatch.delenv("S2C_HOST_ALWAYS_LINK_MBPS", raising=False)
    # tunnel-class link: no bound
    assert host_pileup_max_len(True, link_bps=40e6) == (1 << 62)
    assert host_pileup_max_len(True, link_bps=8e6) == (1 << 62)
    # PCIe-class link: the narrow native-tail bound
    assert host_pileup_max_len(True, link_bps=3e9) == (1 << 23)
    # unknown link (no probe): conservative narrow bound
    assert host_pileup_max_len(True) == (1 << 23)
    # without the native tail the link rate is irrelevant (the tail
    # would ship counts anyway)
    assert host_pileup_max_len(False, link_bps=8e6) == (1 << 21)
    # threshold is env-tunable
    monkeypatch.setenv("S2C_HOST_ALWAYS_LINK_MBPS", "5000")
    assert host_pileup_max_len(True, link_bps=3e9) == (1 << 62)


def test_insertion_kernel_auto_window(monkeypatch):
    """--insertion-kernel auto: pallas only for chip-resident tails in
    the TPU-measured winning event-count window (round-5 fused-vote
    sweep: 0.94x/0.75-0.97x/1.36x/2.28x vs the scatter tail at
    2e4/2e5/2e6/8e6 events — the sub-1e6 regime is round-trip
    dominated)."""
    from sam2consensus_tpu.backends import jax_backend as jb

    monkeypatch.delenv("S2C_PALLAS_INS_MIN_EVENTS", raising=False)
    monkeypatch.delenv("S2C_PALLAS_INS_MAX_EVENTS", raising=False)
    # inside the window, chip tail: pallas
    assert jb._pallas_ins_auto(2_000_000, True)
    assert jb._pallas_ins_auto(8_000_000, True)
    # outside the window: scatter
    assert not jb._pallas_ins_auto(20_000, True)
    assert not jb._pallas_ins_auto(200_000, True)
    assert not jb._pallas_ins_auto(32_000_000, True)
    # host-routed / interpret-mode tail: never pallas
    assert not jb._pallas_ins_auto(200_000, False)
    # default config routes through auto (a RunConfig regression pin)
    assert RunConfig(prefix="t", thresholds=[0.25]).ins_kernel == "auto"


def test_sparse_output_tail_pallas_byte_identical(monkeypatch):
    """The Pallas insertion-kernel variant composes with the sparse
    output encoding."""
    monkeypatch.setenv("S2C_TAIL_ENCODING", "sparse")
    text = simulate(SimSpec(n_contigs=2, contig_len=200_000, n_reads=300,
                            read_len=60, ins_read_rate=0.3,
                            del_read_rate=0.2, seed=47))
    cfg = RunConfig(prefix="t", thresholds=[0.25, 0.75], shards=1)
    out_cpu, _ = _run(text, CpuBackend(), cfg)
    cfg_p = RunConfig(prefix="t", thresholds=[0.25, 0.75], shards=1,
                      ins_kernel="pallas")
    out_jax, st = _run(text, JaxBackend(), cfg_p)
    assert out_jax == out_cpu
    assert st.extra["insertion_kernel"] == "pallas"
    assert st.extra["d2h_bytes"] < 2 * 200_000 * 2, st.extra


def test_overflow_sums_host_fallback():
    """Total aligned bases past int32 route contig sums through the host
    recomputation (the device cumsum is int32); per-position values stay
    int32-safe by construction.  Exercised by resuming from a crafted
    checkpoint whose counts already hold >2^31 events."""
    from sam2consensus_tpu.io.sam import ReadStream, opener
    from sam2consensus_tpu.utils import checkpoint as ckpt
    from sam2consensus_tpu.encoder.events import InsertionEvents

    length = 8
    big = 1 << 29                       # per-lane, per-position: int32-safe
    counts = np.zeros((length, 6), np.int32)
    counts[:, 1] = big                  # 8 * 2^29 = 2^32 total events
    text = ("@SQ\tSN:z\tLN:8\n"
            "r1\t0\tz\t1\t60\t4M\t*\t0\t0\tACGT\t*\n")
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "in.sam")
        with open(path, "w") as fh:
            fh.write(text)
        ckdir = os.path.join(tmp, "ck")
        ckpt.save(ckdir, ckpt.CheckpointState(
            counts=counts, lines_consumed=0, reads_mapped=0,
            reads_skipped=0, aligned_bases=8 * big,
            insertions=InsertionEvents(), byte_offset=-1))

        handle = opener(path, binary=True)
        contigs, _n, first = read_header(handle)
        cfg = RunConfig(prefix="t", thresholds=[0.25], shards=1,
                        checkpoint_dir=ckdir)
        res = JaxBackend().run(contigs, ReadStream(handle, first), cfg)
        handle.close()
        assert res.stats.extra.get("contig_sums_host_fallback") is True
        # header's mean coverage comes from the exact >2^31 sum:
        # (8*2^29 + 4 new bases) / 8 positions = 536870912.5 — an int32
        # cumsum would have wrapped this
        header = res.fastas["z"][0].header
        assert f"coverage:{(8 * big + 4) / 8}" in header, header
        # the called bases are all A — lane 1 in the ASCII-sorted alphabet
        # ('-', A, C, G, N, T); 2^29 As drown the 4 new read bases
        assert res.fastas["z"][0].seq == "AAAAAAAA"


def test_fused_decode_accumulate_equals_two_pass():
    """The C++ fused decode+accumulate path (accumulate_into) produces
    identical counts/read counts to decode-then-walk, including
    python-replayed fallback reads (negative-POS wraps)."""
    from sam2consensus_tpu import native

    if native.load() is None:
        import pytest

        pytest.skip("native decoder unavailable")
    from sam2consensus_tpu.encoder.native_encoder import NativeReadEncoder
    from sam2consensus_tpu.io.sam import ReadStream

    text = simulate(SimSpec(n_contigs=3, contig_len=400, n_reads=500,
                            read_len=60, ins_read_rate=0.2,
                            del_read_rate=0.2, seed=48))
    # negative-POS wrap rides the C slow path
    text += "neg\t0\tcontig0000\t0\t60\t4M\t*\t0\t0\tACGT\t*\n"
    # span 300 > default width (256): overflow -> python-fallback replay,
    # exercising the fused numpy-accumulate branch in _fallback_line
    # (contig0002 is 474 long at this seed, so the span fits)
    text += ("wide\t0\tcontig0002\t1\t60\t2M296D2M\t*\t0\t0\tACGT\t*\n")
    # SEQ shorter than its CIGAR claims: the C decoder flags it and the
    # python replay applies the reference's concatenation semantics
    text += ("short\t0\tcontig0001\t1\t60\t200M\t*\t0\t0\tACGT\t*\n")

    def run(fused):
        handle = io.StringIO(text)
        contigs, _n, first = read_header(handle)
        layout = GenomeLayout(contigs)
        acc = HostPileupAccumulator(layout.total_len)
        enc = NativeReadEncoder(
            layout,
            accumulate_into=acc.counts_host() if fused else None)
        for b in enc.encode_blocks(ReadStream(handle, first).blocks()):
            acc.add(b)
        return acc, enc

    acc_two, enc_two = run(False)
    acc_fused, enc_fused = run(True)
    np.testing.assert_array_equal(acc_two.counts_host(),
                                  acc_fused.counts_host())
    assert enc_two.n_reads == enc_fused.n_reads
    assert acc_fused.strategy_used.get("host_fused", 0) > 0


def test_fused_direct_and_shadow_modes_byte_identical(monkeypatch):
    """The fused pileup's two counting modes — uint8 shadow (+256
    overflow bank, merged at stream end) and direct int32 (huge-genome
    mode, no shadow) — are one semantics: forcing each on the same
    input produces byte-identical output and identical counts vs the
    oracle (round 4: the mode gate is genome size, S2C_FUSED_DIRECT_MIN_LEN)."""
    text = simulate(SimSpec(n_contigs=4, contig_len=300, n_reads=2000,
                            read_len=50, ins_read_rate=0.1,
                            del_read_rate=0.1, seed=77))
    from sam2consensus_tpu.io.sam import ReadStream

    def run_stream(backend, cfg):
        handle = io.StringIO(text)
        contigs, _n, first = read_header(handle)
        res = backend.run(contigs, ReadStream(handle, first), cfg)
        return ({n: render_file(r, 0) for n, r in res.fastas.items()},
                res.stats)

    cfg = RunConfig(prefix="t", thresholds=[0.25, 0.75], shards=1,
                    pileup="host")
    out_cpu, _ = _run(text, CpuBackend(), cfg)
    monkeypatch.setenv("S2C_FUSED_DIRECT_MIN_LEN", "1")   # force direct
    out_direct, st_d = run_stream(JaxBackend(), cfg)
    monkeypatch.setenv("S2C_FUSED_DIRECT_MIN_LEN", str(1 << 60))  # shadow
    out_shadow, st_s = run_stream(JaxBackend(), cfg)
    assert out_direct == out_cpu
    assert out_shadow == out_cpu
    assert st_d.extra["pileup"].get("host_fused", 0) > 0
    assert st_s.extra["pileup"].get("host_fused", 0) > 0
