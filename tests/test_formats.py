"""formats/: BGZF block-parallel decode, BAM ingestion, long-read path.

Four layers of assurance:

* BGZF container units — write/scan/inflate round trips, serial ==
  parallel, truncation and corruption semantics with precise offsets;
* BAM decode units — header/reference-table parity, record-for-record
  equality with the SAM text parser, strict-mode error parity;
* end-to-end byte identity — every committed fixture family
  (``tests/data/formats_*``), in every container flavor, through the
  CPU oracle AND the jax backend (host + device pileup), against the
  pinned ``.expected.fasta``;
* long-read/segmentation adversarial cases and a hypothesis property:
  arbitrary record sets round-trip SAM↔BAM to identical pileup counts
  and identical FASTA.
"""

import gzip
import io
import os
import shutil

import numpy as np
import pytest

from sam2consensus_tpu.backends.cpu import CpuBackend
from sam2consensus_tpu.config import RunConfig
from sam2consensus_tpu.formats import (AlignmentInput, detect_format,
                                       open_alignment_input, sibling_sam)
from sam2consensus_tpu.formats import bgzf
from sam2consensus_tpu.formats.bam import (BamReadStream, BamSegmentEncoder,
                                           bam_payload, read_bam_header,
                                           sam_text_to_bam, write_bam)
from sam2consensus_tpu.io.fasta import render_file
from sam2consensus_tpu.io.sam import (ReadStream, iter_records, opener,
                                      read_header)
from sam2consensus_tpu.utils.simulate import SimSpec, sam_text, simulate

DATA = os.path.join(os.path.dirname(__file__), "data")
FAMILIES = ("formats_short", "formats_longread", "formats_adversarial")


def _header_blob(contigs):
    """Bare BAM header bytes for hand-built corrupt-record payloads."""
    import struct

    text = b""
    out = [b"BAM\x01", struct.pack("<i", len(text)), text,
           struct.pack("<i", len(contigs))]
    for name, ln in contigs:
        raw = name.encode() + b"\x00"
        out += [struct.pack("<i", len(raw)), raw, struct.pack("<i", ln)]
    return b"".join(out)


def _render_all(fastas, contigs):
    return "".join(render_file(fastas[c.name], 0)
                   for c in contigs if c.name in fastas)


def run_backend(path, fmt="auto", backend=None, binary=None, **cfg_kw):
    be = backend or CpuBackend()
    if binary is None:
        binary = be.name == "jax"
    ai = open_alignment_input(path, fmt, binary=binary)
    cfg = RunConfig(prefix="fixture", **cfg_kw)
    res = be.run(ai.contigs, ai.stream, cfg)
    out = _render_all(res.fastas, ai.contigs)
    lines = ai.stream.n_lines
    ai.close()
    return out, res.stats, lines


# ---------------------------------------------------------------------------
# BGZF container
# ---------------------------------------------------------------------------
class TestBgzf:
    PAYLOAD = (b"line one\nline two\n" * 5000) + b"tail without newline"

    def test_roundtrip_serial(self, tmp_path):
        p = str(tmp_path / "x.bgzf")
        bgzf.write_bgzf(self.PAYLOAD, p, block_udata=4096)
        r = bgzf.BgzfReader(p)
        assert r.read() == self.PAYLOAD
        r.close()

    def test_parallel_equals_serial(self, tmp_path):
        p = str(tmp_path / "x.bgzf")
        bgzf.write_bgzf(self.PAYLOAD, p, block_udata=1024)
        r1 = bgzf.BgzfReader(p, threads=1)
        r4 = bgzf.BgzfReader(p, threads=4)
        assert r1.read() == r4.read() == self.PAYLOAD
        r1.close()
        r4.close()

    def test_block_index_tiles_file(self, tmp_path):
        p = str(tmp_path / "x.bgzf")
        bgzf.write_bgzf(self.PAYLOAD, p, block_udata=4096)
        size = os.path.getsize(p)
        with open(p, "rb") as fh:
            blocks = bgzf.scan_blocks(fh)
        assert blocks[0][0] == 0
        assert sum(b[1] for b in blocks) == size
        for (o1, l1), (o2, _l2) in zip(blocks, blocks[1:]):
            assert o1 + l1 == o2

    def test_readline_iteration(self, tmp_path):
        p = str(tmp_path / "x.bgzf")
        bgzf.write_bgzf(self.PAYLOAD, p, block_udata=512)
        r = bgzf.BgzfReader(p, threads=2)
        lines = list(r)
        assert b"".join(lines) == self.PAYLOAD
        assert lines[0] == b"line one\n"
        assert lines[-1] == b"tail without newline"
        r.close()

    def test_tell_and_seek_uncompressed(self, tmp_path):
        p = str(tmp_path / "x.bgzf")
        bgzf.write_bgzf(self.PAYLOAD, p, block_udata=600)
        r = bgzf.BgzfReader(p)
        assert r.tell() == 0
        first = r.read(100)
        assert r.tell() == 100
        r.seek(50)
        assert r.read(50) == first[50:]
        r.close()

    def test_missing_eof_marker_is_truncation(self, tmp_path):
        p = str(tmp_path / "x.bgzf")
        bgzf.write_bgzf(self.PAYLOAD, p, block_udata=4096)
        with open(p, "rb") as fh:
            data = fh.read()
        clipped = str(tmp_path / "trunc.bgzf")
        with open(clipped, "wb") as fh:
            fh.write(data[: -len(bgzf.BGZF_EOF)])
        with pytest.raises(bgzf.BgzfTruncation):
            bgzf.BgzfReader(clipped)

    def test_midblock_truncation_has_offset(self, tmp_path):
        p = str(tmp_path / "x.bgzf")
        bgzf.write_bgzf(self.PAYLOAD, p, block_udata=4096)
        with open(p, "rb") as fh:
            data = fh.read()
        clipped = str(tmp_path / "trunc.bgzf")
        with open(clipped, "wb") as fh:
            fh.write(data[: len(data) // 2])
        with pytest.raises(bgzf.BgzfTruncation) as ei:
            bgzf.BgzfReader(clipped)
        assert ei.value.offset >= 0

    def test_corrupt_block_offset_and_transient(self, tmp_path):
        p = str(tmp_path / "x.bgzf")
        bgzf.write_bgzf(self.PAYLOAD, p, block_udata=4096)
        with open(p, "rb") as fh:
            blocks = bgzf.scan_blocks(fh)
            data = bytearray(fh.read())
        # flip a payload byte inside the SECOND block
        off, length = blocks[1]
        data[off + 20] ^= 0xFF
        bad = str(tmp_path / "bad.bgzf")
        with open(bad, "wb") as fh:
            fh.write(bytes(data))
        r = bgzf.BgzfReader(bad)
        with pytest.raises(bgzf.BgzfCorruptBlock) as ei:
            r.read()
        assert ei.value.offset == off
        r.close()
        # resilience vocabulary: storage bitrot is transport-shaped
        from sam2consensus_tpu.resilience.policy import TRANSIENT, classify

        assert classify(ei.value) == TRANSIENT

    def test_plain_gzip_is_not_bgzf(self, tmp_path):
        p = str(tmp_path / "x.gz")
        with gzip.open(p, "wb") as fh:
            fh.write(self.PAYLOAD)
        assert not bgzf.is_bgzf(p)
        with open(p, "rb") as fh:
            with pytest.raises(bgzf.BgzfError):
                bgzf.scan_blocks(fh)

    def test_sniff_needs_bc_subfield(self):
        assert not bgzf.sniff_bgzf(b"\x1f\x8b\x08\x04" + b"\x00" * 20)
        assert bgzf.sniff_bgzf(bgzf.BGZF_EOF)


# ---------------------------------------------------------------------------
# format detection / routing
# ---------------------------------------------------------------------------
class TestDetectionAndRouting:
    def test_detect_fixture_flavors(self):
        assert detect_format(os.path.join(DATA, "formats_short.sam")) \
            == "sam"
        assert detect_format(os.path.join(DATA, "formats_short.bam")) \
            == "bam"
        assert detect_format(os.path.join(DATA, "formats_short.sam.gz")) \
            == "sam.bgzf"
        assert detect_format(
            os.path.join(DATA, "formats_short.plain.sam.gz")) == "sam.gz"

    def test_opener_routes_bgzf_gz(self):
        """Satellite: htslib-style .sam.gz (BGZF) gets the block-parallel
        reader; plain gzip keeps the serial path; contents identical."""
        h = opener(os.path.join(DATA, "formats_short.sam.gz"),
                   binary=True, threads=2)
        assert isinstance(h, bgzf.BgzfReader)
        bgzf_bytes = h.read()
        h.close()
        h = opener(os.path.join(DATA, "formats_short.plain.sam.gz"),
                   binary=True)
        assert isinstance(h, gzip.GzipFile)
        plain_bytes = h.read()
        h.close()
        with open(os.path.join(DATA, "formats_short.sam"), "rb") as fh:
            assert bgzf_bytes == plain_bytes == fh.read()

    def test_opener_text_mode_over_bgzf(self):
        h = opener(os.path.join(DATA, "formats_short.sam.gz"))
        first = h.readline()
        assert isinstance(first, str) and first.startswith("@")
        h.close()

    def test_open_alignment_contigs_agree(self):
        ais = [open_alignment_input(
            os.path.join(DATA, f"formats_short{ext}"))
            for ext in (".sam", ".bam", ".sam.gz", ".plain.sam.gz")]
        names = [[c.name for c in ai.contigs] for ai in ais]
        lens = [[c.length for c in ai.contigs] for ai in ais]
        for ai in ais:
            ai.close()
        assert all(n == names[0] for n in names)
        assert all(ln == lens[0] for ln in lens)

    def test_unknown_format_rejected(self):
        with pytest.raises(ValueError):
            open_alignment_input(os.path.join(DATA, "formats_short.sam"),
                                 "cram")

    def test_fallback_to_sibling_sam(self, tmp_path):
        src = os.path.join(DATA, "formats_short.bam")
        bam = str(tmp_path / "job.bam")
        sam = str(tmp_path / "job.sam")
        with open(src, "rb") as fh:
            data = fh.read()
        with open(bam, "wb") as fh:
            fh.write(data[: -len(bgzf.BGZF_EOF)])     # truncate: no EOF
        shutil.copy(os.path.join(DATA, "formats_short.sam"), sam)
        from sam2consensus_tpu import observability as obs

        robs = obs.start_run()
        try:
            ai = open_alignment_input(bam, "auto")
            assert ai.format == "sam"
            assert ai.fallback_from == bam
            reg = obs.metrics()
            assert reg.value("format/bgzf_corrupt") == 1
            assert reg.value("format/fallback") == 1
            ai.close()
        finally:
            obs.finish_run(robs)

    def test_no_sibling_raises_with_offset(self, tmp_path):
        src = os.path.join(DATA, "formats_short.bam")
        bam = str(tmp_path / "lonely.bam")
        with open(src, "rb") as fh:
            data = fh.read()
        with open(bam, "wb") as fh:
            fh.write(data[: -len(bgzf.BGZF_EOF)])
        with pytest.raises(bgzf.BgzfTruncation) as ei:
            open_alignment_input(bam, "auto")
        assert ei.value.offset >= 0
        assert sibling_sam(bam) is None

    def test_sibling_resolution(self, tmp_path):
        sam = tmp_path / "x.sam"
        sam.write_text("@HD\n")
        assert sibling_sam(str(tmp_path / "x.bam")) == str(sam)
        assert sibling_sam(str(tmp_path / "x.sam.gz")) == str(sam)


# ---------------------------------------------------------------------------
# BAM decode parity
# ---------------------------------------------------------------------------
class TestBamDecode:
    def test_header_matches_sam(self):
        with open(os.path.join(DATA, "formats_short.sam")) as fh:
            sam_contigs, _n, _f = read_header(fh)
        r = bgzf.BgzfReader(os.path.join(DATA, "formats_short.bam"))
        bam_contigs, text = read_bam_header(r)
        r.close()
        assert bam_contigs == sam_contigs
        assert "@SQ" in text

    @pytest.mark.parametrize("family", FAMILIES)
    def test_records_match_sam_parser(self, family):
        with open(os.path.join(DATA, f"{family}.sam")) as fh:
            _c, _n, first = read_header(fh)
            sam_recs = list(iter_records(fh, first))
        ai = open_alignment_input(os.path.join(DATA, f"{family}.bam"))
        bam_recs = list(ai.stream.records())
        n_lines = ai.stream.n_lines
        ai.close()
        assert len(bam_recs) == len(sam_recs)
        for s, b in zip(sam_recs, bam_recs):
            assert (b.refname, b.pos, b.cigar, b.seq) \
                == (s.refname, s.pos, s.cigar, s.seq)
        # EVERY record (unmapped included) counts, like SAM body lines
        with open(os.path.join(DATA, f"{family}.sam")) as fh:
            body = sum(1 for ln in fh if not ln.startswith("@"))
        assert n_lines == body

    def test_unknown_reference_error_parity(self, tmp_path):
        text = sam_text([("k1", 100)], [("k1", 5, "4M", "ACGT")])
        # hand-build a BAM whose record points at refID -1 ("*")
        payload = bam_payload([("k1", 100)],
                              [("*", 4, "4M", "ACGT")])
        p = str(tmp_path / "bad.bam")
        bgzf.write_bgzf(payload, p)
        with pytest.raises(KeyError, match="unknown reference"):
            run_backend(p, backend=_jax())
        with pytest.raises(KeyError, match="unknown reference"):
            run_backend(p)
        out, stats, _ = run_backend(p, strict=False)
        assert stats.reads_skipped == 1 and out == ""
        del text

    def test_out_of_bounds_error_parity(self, tmp_path):
        payload = bam_payload([("k1", 10)], [("k1", 8, "6M", "ACGTAC")])
        p = str(tmp_path / "oob.bam")
        bgzf.write_bgzf(payload, p)
        with pytest.raises(IndexError, match="outside reference"):
            run_backend(p)
        with pytest.raises(IndexError, match="outside reference"):
            run_backend(p, backend=_jax())

    def test_invalid_nibble_error_parity(self, tmp_path):
        # 'R' is a legal BAM nibble but outside the ACGTN input contract
        payload = bam_payload([("k1", 100)], [("k1", 0, "4M", "ACRT")])
        p = str(tmp_path / "amb.bam")
        bgzf.write_bgzf(payload, p)
        with pytest.raises(KeyError, match="out-of-alphabet"):
            run_backend(p)
        with pytest.raises(KeyError, match="out-of-alphabet"):
            run_backend(p, backend=_jax())
        _out, stats, _ = run_backend(p, strict=False)
        assert stats.reads_skipped == 1

    def test_encoder_lane_selection(self):
        """decoder=auto engages the C++ binary record decoder when the
        library builds; --decoder py forces the portable python twin."""
        from sam2consensus_tpu.encoder import native_encoder
        from sam2consensus_tpu.encoder.events import GenomeLayout
        from sam2consensus_tpu.formats.bam import NativeBamEncoder

        ai = open_alignment_input(os.path.join(DATA, "formats_short.bam"))
        layout = GenomeLayout(ai.contigs)
        enc, batches = ai.stream.make_encoder(layout,
                                              RunConfig(prefix="x"))
        expected_cls = NativeBamEncoder if native_encoder.available() \
            else BamSegmentEncoder
        assert isinstance(enc, expected_cls)
        assert sum(b.n_events for b in batches) > 0
        assert enc.n_reads > 0
        ai.close()
        ai = open_alignment_input(os.path.join(DATA, "formats_short.bam"))
        enc, _b = ai.stream.make_encoder(
            GenomeLayout(ai.contigs), RunConfig(prefix="x", decoder="py"))
        assert isinstance(enc, BamSegmentEncoder)
        ai.close()

    @pytest.mark.parametrize("family", FAMILIES)
    def test_native_and_python_decoders_agree(self, family):
        """The C++ record decoder and the pure-python twin produce the
        same counts, insertions and stats over every fixture family."""
        from sam2consensus_tpu.encoder import native_encoder
        from sam2consensus_tpu.encoder.events import (GenomeLayout,
                                                      group_insertions)

        if not native_encoder.available():
            pytest.skip("native decoder unavailable")
        path = os.path.join(DATA, f"{family}.bam")
        results = []
        count_tensors = []
        for decoder in ("native", "py"):
            ai = open_alignment_input(path)
            layout = GenomeLayout(ai.contigs)
            counts = np.zeros((layout.total_len, 6), dtype=np.int64)
            enc, batches = ai.stream.make_encoder(
                layout, RunConfig(prefix="x", decoder=decoder))
            for b in batches:
                for _w, (starts, codes) in b.buckets.items():
                    rows, cols = np.nonzero(codes != 255)
                    np.add.at(counts,
                              (starts[rows].astype(np.int64) + cols,
                               codes[rows, cols]), 1)
            grouped = group_insertions(enc.insertions, layout)
            results.append((
                enc.n_reads, enc.n_skipped, ai.stream.n_lines,
                None if grouped is None else
                (tuple(grouped["key_flat"].tolist()),
                 grouped["max_cols"], int(grouped["ev_code"].sum()))))
            count_tensors.append(counts)
            ai.close()
        assert results[0] == results[1]
        assert np.array_equal(count_tensors[0], count_tensors[1])


# ---------------------------------------------------------------------------
# end-to-end byte identity against the pinned oracle outputs
# ---------------------------------------------------------------------------
def _jax():
    from sam2consensus_tpu.backends.jax_backend import JaxBackend

    return JaxBackend()


class TestEndToEndIdentity:
    @pytest.mark.parametrize("family", FAMILIES)
    @pytest.mark.parametrize("ext", [".sam", ".bam", ".sam.gz",
                                     ".plain.sam.gz"])
    def test_cpu_oracle_every_flavor(self, family, ext):
        with open(os.path.join(DATA, f"{family}.expected.fasta")) as fh:
            expected = fh.read()
        out, _s, _l = run_backend(os.path.join(DATA, family + ext))
        assert out == expected

    @pytest.mark.parametrize("family", FAMILIES)
    @pytest.mark.parametrize("ext", [".bam", ".sam.gz"])
    def test_jax_backend_every_flavor(self, family, ext):
        with open(os.path.join(DATA, f"{family}.expected.fasta")) as fh:
            expected = fh.read()
        out, _s, _l = run_backend(os.path.join(DATA, family + ext),
                                  backend=_jax())
        assert out == expected

    @pytest.mark.parametrize("family", ["formats_longread",
                                        "formats_adversarial"])
    def test_jax_device_pileup_bam(self, family):
        """Long-read/adversarial BAM through the DEVICE scatter path —
        the segmented slabs must land the same counts the oracle got."""
        with open(os.path.join(DATA, f"{family}.expected.fasta")) as fh:
            expected = fh.read()
        out, _s, _l = run_backend(os.path.join(DATA, f"{family}.bam"),
                                  backend=_jax(), pileup="scatter")
        assert out == expected

    def test_line_totals_agree_across_flavors(self):
        totals = set()
        for ext in (".sam", ".bam", ".sam.gz", ".plain.sam.gz"):
            _o, _s, lines = run_backend(
                os.path.join(DATA, "formats_short" + ext))
            totals.add(lines)
        assert len(totals) == 1

    def test_longread_delta8_wire_escape_lanes(self):
        """Segmented long-read slabs through the delta8 row codec +
        device scatter: segment starts jump by W per row (escape-lane
        traffic for the uint8 delta stream) and the 300-base insertion
        run rides the escape list — counts must stay byte-exact."""
        for family in ("formats_longread", "formats_adversarial"):
            with open(os.path.join(DATA,
                                   f"{family}.expected.fasta")) as fh:
                expected = fh.read()
            out, _s, _l = run_backend(
                os.path.join(DATA, f"{family}.bam"), backend=_jax(),
                pileup="scatter", wire="delta8")
            assert out == expected

    def test_segmentation_choices_are_byte_identical(self):
        base = None
        for seg_w in (0, 128, 1 << 20, -1):
            out, _s, _l = run_backend(
                os.path.join(DATA, "formats_longread.bam"),
                backend=_jax(), segment_width=seg_w)
            if base is None:
                base = out
            assert out == base
        with open(os.path.join(DATA,
                               "formats_longread.expected.fasta")) as fh:
            assert base == fh.read()


# ---------------------------------------------------------------------------
# long-read segmentation units
# ---------------------------------------------------------------------------
class TestSegmentedLayout:
    def _encode(self, text, seg_w, **cfg_kw):
        from sam2consensus_tpu.encoder.events import (GenomeLayout,
                                                      ReadEncoder)

        handle = io.StringIO(text)
        contigs, _n, first = read_header(handle)
        enc = ReadEncoder(GenomeLayout(contigs), segment_width=seg_w,
                          **cfg_kw)
        batches = list(enc.encode_segments(iter_records(handle, first)))
        return enc, batches

    def test_wide_read_splits_exactly(self):
        text = sam_text([("c", 9000)], [("c", 11, "8000M", "A" * 8000)])
        _enc, batches = self._encode(text, 512)
        rows = [(int(s), c) for b in batches
                for w, (starts, codes) in b.buckets.items()
                for s, c in zip(starts, codes)
                if (c != 255).any()]
        assert len(rows) == 8000 // 512 + 1
        # reconstruct: segments must tile [10, 8010) contiguously
        covered = np.zeros(9000, dtype=int)
        for start, codes in rows:
            real = np.nonzero(codes != 255)[0]
            covered[start + real] += 1
        assert covered[10:8010].min() == 1 and covered[10:8010].max() == 1
        assert covered.sum() == 8000
        # bucket width stays bounded by W, not the span
        assert all(w <= 512 for b in batches for w in b.buckets)

    def test_segment_width_resolution(self):
        from sam2consensus_tpu.encoder.events import (DEFAULT_SEGMENT_W,
                                                      resolve_segment_width)

        assert resolve_segment_width(0) == DEFAULT_SEGMENT_W
        assert resolve_segment_width(-1) == 0
        assert resolve_segment_width(100) == 128
        assert resolve_segment_width(4096) == 4096

    def test_native_width_capped_by_segmentation(self):
        from sam2consensus_tpu.encoder import native_encoder

        if not native_encoder.available():
            pytest.skip("native decoder unavailable")
        from sam2consensus_tpu.encoder.events import GenomeLayout

        text = sam_text(
            [("c", 50000)],
            [("c", 1, "20000M", "A" * 20000)]
            + [("c", i * 40 + 1, "100M", "C" * 100) for i in range(800)])
        contigs, _n, first = read_header(io.StringIO(text))
        enc = native_encoder.NativeReadEncoder(
            GenomeLayout(contigs), segment_width=1024)
        widths = {w for b in enc.encode_blocks([text.split("\n", 2)[2]])
                  for w in b.buckets}
        assert max(widths) <= 1024
        assert enc.width <= 1024

    def test_insertion_run_over_255(self):
        text = sam_text(
            [("c", 400)],
            [("c", 101, "50M300I50M", "A" * 50 + "G" * 300 + "T" * 50),
             ("c", 101, "100M", "A" * 100)])
        handle = io.StringIO(text)
        contigs, _n, first = read_header(handle)
        cfg = RunConfig(prefix="x")
        res = CpuBackend().run(contigs, iter_records(handle, first), cfg)
        out = _render_all(res.fastas, contigs)
        # 300-base insertion called at full depth 1 of 2... vote may gap;
        # identity with jax is the real assertion
        ai_text = io.StringIO(text)
        contigs2, _n2, first2 = read_header(ai_text)
        from sam2consensus_tpu.encoder.events import (GenomeLayout,
                                                      group_insertions)

        enc, _b = self._encode(text, 128)
        grouped = group_insertions(enc.insertions,
                                   GenomeLayout(contigs2))
        assert grouped["max_cols"] == 300
        assert out  # oracle rendered something
        del first2

    def test_all_indel_read(self):
        text = sam_text(
            [("c", 500)],
            [("c", 101, "40I100D10S", "A" * 50),
             ("c", 141, "60M", "C" * 60)])
        enc, batches = self._encode(text, 64)
        # the D-run row is all GAP codes, segmented into 64-wide rows
        assert sum(b.n_events for b in batches) == 100 + 60
        assert len(enc.insertions) == 1

    def test_longread_decision_in_ledger(self):
        """The segmented-vs-fixed layout choice is a priced, recorded
        decision: it lands in the run manifest with its inputs."""
        from sam2consensus_tpu import observability as obs

        run_backend(os.path.join(DATA, "formats_longread.bam"),
                    backend=_jax())
        man = obs.last_manifest()
        assert man is not None
        decs = {d["decision"]: d for d in man["decisions"]}
        assert decs["longread_layout"]["chosen"] == "segmented"
        assert decs["longread_layout"]["inputs"]["segment_width"] > 0
        # forcing it off records the alternative
        run_backend(os.path.join(DATA, "formats_longread.bam"),
                    backend=_jax(), segment_width=-1)
        decs = {d["decision"]: d
                for d in obs.last_manifest()["decisions"]}
        assert decs["longread_layout"]["chosen"] == "fixed"


# ---------------------------------------------------------------------------
# fault injection / resilience wiring
# ---------------------------------------------------------------------------
class TestBamInflateFaults:
    def test_one_shot_fault_is_absorbed(self, tmp_path):
        """A single injected inflate fault == one-shot bitrot: the
        reader's transient retry absorbs it and the run stays correct."""
        from sam2consensus_tpu.resilience import faultinject

        with open(os.path.join(DATA,
                               "formats_short.expected.fasta")) as fh:
            expected = fh.read()
        faultinject.configure("bam_inflate:rpc:1:1")
        try:
            out, _s, _l = run_backend(
                os.path.join(DATA, "formats_short.bam"))
        finally:
            faultinject.configure("")
        assert out == expected

    def test_persistent_fault_surfaces(self):
        from sam2consensus_tpu.resilience import faultinject

        faultinject.configure("bam_inflate:rpc:1:inf")
        try:
            with pytest.raises(ConnectionError):
                run_backend(os.path.join(DATA, "formats_short.bam"))
        finally:
            faultinject.configure("")

    def test_site_is_registered(self):
        from sam2consensus_tpu.resilience.faultinject import SITES

        assert "bam_inflate" in SITES


# ---------------------------------------------------------------------------
# CLI + serve integration
# ---------------------------------------------------------------------------
class TestCliAndServe:
    def test_cli_bam_end_to_end(self, tmp_path, capsys):
        from sam2consensus_tpu.cli import main

        out_dir = str(tmp_path / "out")
        rc = main(["-i", os.path.join(DATA, "formats_short.bam"),
                   "-o", out_dir, "-p", "fixture", "--format", "bam",
                   "--backend", "jax", "--quiet"])
        assert rc == 0
        produced = sorted(os.listdir(out_dir))
        assert produced
        joined = "".join(
            open(os.path.join(out_dir, f)).read() for f in produced)
        with open(os.path.join(DATA,
                               "formats_short.expected.fasta")) as fh:
            assert joined == fh.read()

    def test_cli_progress_counts_bam_records(self, capsys):
        from sam2consensus_tpu.cli import main
        import tempfile

        with tempfile.TemporaryDirectory() as out_dir:
            main(["-i", os.path.join(DATA, "formats_short.bam"),
                  "-o", out_dir, "-p", "fixture"])
        cap = capsys.readouterr().out
        assert "references found" in cap
        assert "reads were processed" in cap

    def test_serve_mixed_format_queue(self, tmp_path):
        """One warm server, SAM job then BAM job of the same corpus:
        both byte-identical to the pinned oracle output."""
        from sam2consensus_tpu.serve import JobSpec, ServeRunner

        out1 = str(tmp_path / "o1")
        out2 = str(tmp_path / "o2")
        specs = []
        for path, outf in ((os.path.join(DATA, "formats_short.sam"),
                            out1),
                           (os.path.join(DATA, "formats_short.bam"),
                            out2)):
            cfg = RunConfig(prefix="fixture", backend="jax",
                            outfolder=outf + "/")
            os.makedirs(outf)
            specs.append(JobSpec(filename=path, config=cfg))
        runner = ServeRunner(prewarm="off", echo=lambda *a, **k: None)
        try:
            results = runner.submit_jobs(specs)
        finally:
            runner.close()
        assert all(r.ok for r in results)
        texts = []
        for r in results:
            texts.append("".join(
                render_file(v, 0) for _k, v in sorted(r.fastas.items())))
        assert texts[0] == texts[1]


# ---------------------------------------------------------------------------
# review regressions
# ---------------------------------------------------------------------------
class TestReviewRegressions:
    def test_bad_op_code_on_non_max_element(self, tmp_path):
        """decode_ops must flag an invalid op code on ANY element, not
        just the maximum u32 (a long M op used to mask a corrupt op)."""
        import struct

        from sam2consensus_tpu.formats.bam import (BamParseError,
                                                   decode_ops)

        raw = struct.pack("<II", (100 << 4) | 0, (1 << 4) | 10)
        arr = np.frombuffer(raw, dtype=np.uint8)
        with pytest.raises(BamParseError, match="op code 10"):
            decode_ops(arr, 0, 2)

    def test_wide_reads_fill_slab_without_hanging(self, tmp_path):
        """Non-fused (device-path) BAM ingest of enough segmented long
        reads to overrun the slab's row capacity must flush and keep
        going — the capacity handler used to grow insertion buffers
        forever instead."""
        import signal

        from sam2consensus_tpu.encoder.events import GenomeLayout

        text = simulate(SimSpec(
            n_contigs=1, contig_len=40_000, n_reads=800, read_len=10_000,
            ins_read_rate=0, del_read_rate=0, softclip_rate=0,
            sub_rate=0, n_rate=0, contig_len_jitter=0.0, seed=31,
            contig_prefix="wide"))
        bam = str(tmp_path / "wide.bam")
        sam_text_to_bam(text, bam)
        ai = open_alignment_input(bam)
        layout = GenomeLayout(ai.contigs)
        enc, batches = ai.stream.make_encoder(
            layout, RunConfig(prefix="x"), acc=None)
        old = signal.alarm(120)          # regression guard: was a hang
        try:
            n_events = sum(b.n_events for b in batches)
        finally:
            signal.alarm(old)
        assert n_events == 800 * 10_000
        assert enc.n_reads == 800
        ai.close()

    def test_python_lane_rejects_field_overrun(self, tmp_path):
        """A record whose l_seq overruns its block_size must raise
        BamParseError with the offset in BOTH decoder lanes (the python
        lane used to crash with a raw numpy IndexError, or silently
        read the next record's bytes as SEQ)."""
        import struct

        from sam2consensus_tpu.formats.bam import (BamParseError,
                                                   encode_bam_record)

        good = encode_bam_record(0, 0, "4M", "ACGT")
        # corrupt the record's l_seq (offset 4+16) to overrun the block
        bad = bytearray(good)
        struct.pack_into("<i", bad, 4 + 16, 1000)
        payload = (_header_blob([("k1", 100)]) + bytes(bad) + good)
        p = str(tmp_path / "overrun.bam")
        bgzf.write_bgzf(payload, p)
        for decoder in ("py", "native"):
            ai = open_alignment_input(p)
            from sam2consensus_tpu.encoder.events import GenomeLayout

            enc, batches = ai.stream.make_encoder(
                GenomeLayout(ai.contigs),
                RunConfig(prefix="x", decoder=decoder))
            with pytest.raises(BamParseError, match="overrun"):
                list(batches)
            ai.close()

    def test_serve_journal_rejects_bam_up_front(self, tmp_path):
        """Journal mode checkpoints every job and BAM has no checkpoint
        resume: the queue must fail at submission, not journal each BAM
        job failed twice."""
        from sam2consensus_tpu.serve import JobSpec, ServeRunner

        runner = ServeRunner(prewarm="off",
                             journal_dir=str(tmp_path / "j"),
                             echo=lambda *a, **k: None)
        try:
            spec = JobSpec(
                filename=os.path.join(DATA, "formats_short.bam"),
                config=RunConfig(prefix="x", backend="jax",
                                 outfolder=str(tmp_path) + "/"))
            with pytest.raises(ValueError, match="BAM input"):
                runner.submit_jobs([spec])
        finally:
            runner.close()


# ---------------------------------------------------------------------------
# seeded pseudo-property round trip (hypothesis-free twin of
# tests/test_formats_property.py, which runs when hypothesis is
# installed — this one always runs)
# ---------------------------------------------------------------------------
class TestRoundTripSeeded:
    @pytest.mark.parametrize("seed", [11, 23, 47])
    def test_random_corpus_sam_bam_identical(self, seed, tmp_path):
        text = simulate(SimSpec(
            n_contigs=2, contig_len=300, n_reads=250, read_len=40,
            ins_read_rate=0.2, del_read_rate=0.2, softclip_rate=0.15,
            seed=seed))
        sam = str(tmp_path / "x.sam")
        bam = str(tmp_path / "x.bam")
        with open(sam, "w") as fh:
            fh.write(text)
        sam_text_to_bam(text, bam)
        out_s, stats_s, lines_s = run_backend(sam)
        out_b, stats_b, lines_b = run_backend(bam)
        assert out_s == out_b
        assert stats_s.aligned_bases == stats_b.aligned_bases
        assert stats_s.reads_mapped == stats_b.reads_mapped
        assert lines_s == lines_b
        out_jb, _st, _l = run_backend(bam, backend=_jax())
        assert out_jb == out_s
