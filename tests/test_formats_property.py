"""Hypothesis property: arbitrary record sets round-trip SAM↔BAM to
identical pileup counts and identical FASTA vs the cpu oracle.

Separate module so environments without hypothesis (the ``[dev]``
extra) skip ONLY the property layer — tests/test_formats.py carries a
seeded pseudo-property twin that always runs.
"""

import os
import sys

import pytest

pytest.importorskip("hypothesis")
from hypothesis import HealthCheck, given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sam2consensus_tpu.formats.bam import sam_text_to_bam  # noqa: E402
from sam2consensus_tpu.utils.simulate import sam_text  # noqa: E402

from test_formats import _jax, run_backend  # noqa: E402

_BASE = st.sampled_from("ACGTN")
_OP = st.sampled_from("MIDNS")


@st.composite
def _read(draw):
    n_ops = draw(st.integers(1, 5))
    cigar = []
    seq = []
    span = 0
    for _ in range(n_ops):
        o = draw(_OP)
        n = draw(st.integers(1, 12))
        cigar.append(f"{n}{o}")
        if o == "M":
            seq.append("".join(draw(_BASE) for _ in range(n)))
            span += n
        elif o in "DN":
            span += n
        else:                       # I / S consume read only
            seq.append("".join(draw(_BASE) for _ in range(n)))
    pos = draw(st.integers(1, max(1, 150 - span)))
    return ("c0", pos, "".join(cigar), "".join(seq) or "*")


@settings(max_examples=25, deadline=None,
          suppress_health_check=[HealthCheck.function_scoped_fixture])
@given(st.lists(_read(), min_size=1, max_size=10))
def test_sam_bam_round_trip_identical(tmp_path, reads):
    text = sam_text([("c0", 200)], reads)
    sam = str(tmp_path / "x.sam")
    bam = str(tmp_path / "x.bam")
    with open(sam, "w") as fh:
        fh.write(text)
    sam_text_to_bam(text, bam)
    out_s, stats_s, lines_s = run_backend(sam)
    out_b, stats_b, lines_b = run_backend(bam)
    assert out_s == out_b
    assert stats_s.aligned_bases == stats_b.aligned_bases
    assert stats_s.reads_mapped == stats_b.reads_mapped
    assert lines_s == lines_b
    out_jb, _st, _l = run_backend(bam, backend=_jax())
    assert out_jb == out_s
