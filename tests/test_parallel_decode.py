"""Multi-threaded fused decode: exact parity with the serial path.

The count tensor is sum-decomposable, so per-worker tensors summed at the
end must equal the serial fused pass bit-for-bit; insertion grouping
sorts by site key, so store concatenation order is irrelevant; strict
errors must surface as the FIRST bad line of the stream exactly like the
serial path (encoder/parallel_decode.py).
"""

import io

import numpy as np
import pytest

from sam2consensus_tpu import native
from sam2consensus_tpu.backends.cpu import CpuBackend
from sam2consensus_tpu.backends.jax_backend import JaxBackend
from sam2consensus_tpu.config import RunConfig
from sam2consensus_tpu.encoder.events import GenomeLayout
from sam2consensus_tpu.io.fasta import render_file
from sam2consensus_tpu.io.sam import ReadStream, read_header
from sam2consensus_tpu.ops.pileup import HostPileupAccumulator
from sam2consensus_tpu.utils.simulate import SimSpec, simulate

pytestmark = pytest.mark.skipif(native.load() is None,
                                reason="native decoder unavailable")


def _decode(text, n_threads, block_bytes=4096):
    from sam2consensus_tpu.encoder.parallel_decode import \
        ParallelFusedDecoder

    handle = io.StringIO(text)
    contigs, _n, first = read_header(handle)
    layout = GenomeLayout(contigs)
    acc = HostPileupAccumulator(layout.total_len)
    dec = ParallelFusedDecoder(layout, acc.counts_host(), n_threads)
    stream = ReadStream(handle, first)
    events = 0
    for b in dec.encode_blocks(stream.blocks(max_bytes=block_bytes)):
        acc.add(b)
        events += b.n_events
    return acc, dec, events


@pytest.mark.parametrize("n_threads", [1, 2, 4])
def test_parallel_counts_equal_serial(n_threads):
    text = simulate(SimSpec(n_contigs=4, contig_len=300, n_reads=1200,
                            read_len=60, ins_read_rate=0.2,
                            del_read_rate=0.2, seed=51))
    acc1, dec1, ev1 = _decode(text, 1)
    accn, decn, evn = _decode(text, n_threads)
    np.testing.assert_array_equal(acc1.counts_host(), accn.counts_host())
    assert dec1.n_reads == decn.n_reads
    assert dec1.n_skipped == decn.n_skipped
    assert ev1 == evn
    assert len(dec1.insertions) == len(decn.insertions)


def test_parallel_error_is_first_bad_line():
    """A bad line mid-stream raises the SAME first error regardless of
    which worker hits which block."""
    text = simulate(SimSpec(n_contigs=2, contig_len=200, n_reads=400,
                            read_len=40, seed=52))
    lines = text.splitlines(keepends=True)
    # malformed body line (too few fields -> IndexError parity) spliced
    # near the middle, then another later — only the FIRST must surface
    mid = len(lines) // 2
    lines.insert(mid, "broken\tline\n")
    lines.insert(mid + 50, "also\tbroken\n")
    bad_text = "".join(lines)

    errs = []
    for n_threads in (1, 3):
        with pytest.raises(Exception) as ei:
            _decode(bad_text, n_threads, block_bytes=1024)
        errs.append((type(ei.value), str(ei.value)))
    assert errs[0] == errs[1]


def _run_cli_style(text, cfg):
    handle = io.StringIO(text)
    contigs, _n, first = read_header(handle)
    res = JaxBackend().run(contigs, ReadStream(handle, first), cfg)
    return {n: render_file(r, 0) for n, r in res.fastas.items()}


def test_backend_decode_threads_byte_identical():
    text = simulate(SimSpec(n_contigs=3, contig_len=250, n_reads=900,
                            read_len=50, ins_read_rate=0.25,
                            del_read_rate=0.15, seed=53))
    handle = io.StringIO(text)
    contigs, _n, first = read_header(handle)
    from sam2consensus_tpu.io.sam import iter_records
    res_cpu = CpuBackend().run(contigs, iter_records(handle, first),
                               RunConfig(prefix="t", thresholds=[0.25]))
    want = {n: render_file(r, 0) for n, r in res_cpu.fastas.items()}

    got = _run_cli_style(text, RunConfig(prefix="t", thresholds=[0.25],
                                         shards=1, decode_threads=3))
    assert got == want
