"""Sharded multi-core ingest: exact parity with the serial path.

The count tensor is sum-decomposable, so per-worker partitions merged at
the end must equal the serial fused pass bit-for-bit; insertion grouping
sorts by site key, so store concatenation order is irrelevant; strict
errors must surface as the FIRST bad line of the stream exactly like the
serial path — on the byte-shard rung (disjoint ordered ranges: earliest
shard wins) AND the streaming rung (block order within workers)
(encoder/parallel_decode.py)."""

import io
import os

import numpy as np
import pytest

from sam2consensus_tpu import ingest, native, observability
from sam2consensus_tpu.backends.cpu import CpuBackend
from sam2consensus_tpu.backends.jax_backend import JaxBackend
from sam2consensus_tpu.config import RunConfig
from sam2consensus_tpu.encoder.events import GenomeLayout
from sam2consensus_tpu.encoder.native_encoder import NativeReadEncoder
from sam2consensus_tpu.io.fasta import render_file
from sam2consensus_tpu.io.sam import ReadStream, opener, read_header
from sam2consensus_tpu.ops.pileup import HostPileupAccumulator
from sam2consensus_tpu.utils.simulate import SimSpec, simulate

pytestmark = pytest.mark.skipif(native.load() is None,
                                reason="native decoder unavailable")


def _decode(text, n_threads, block_bytes=4096):
    from sam2consensus_tpu.encoder.parallel_decode import \
        ParallelFusedDecoder

    handle = io.StringIO(text)
    contigs, _n, first = read_header(handle)
    layout = GenomeLayout(contigs)
    acc = HostPileupAccumulator(layout.total_len)
    dec = ParallelFusedDecoder(layout, acc.counts_host(), n_threads)
    stream = ReadStream(handle, first)
    events = 0
    for b in dec.encode_blocks(stream.blocks(max_bytes=block_bytes)):
        acc.add(b)
        events += b.n_events
    return acc, dec, events


@pytest.mark.parametrize("n_threads", [1, 2, 4])
def test_parallel_counts_equal_serial(n_threads):
    text = simulate(SimSpec(n_contigs=4, contig_len=300, n_reads=1200,
                            read_len=60, ins_read_rate=0.2,
                            del_read_rate=0.2, seed=51))
    acc1, dec1, ev1 = _decode(text, 1)
    accn, decn, evn = _decode(text, n_threads)
    np.testing.assert_array_equal(acc1.counts_host(), accn.counts_host())
    assert dec1.n_reads == decn.n_reads
    assert dec1.n_skipped == decn.n_skipped
    assert ev1 == evn
    assert len(dec1.insertions) == len(decn.insertions)


def test_parallel_error_is_first_bad_line():
    """A bad line mid-stream raises the SAME first error regardless of
    which worker hits which block."""
    text = simulate(SimSpec(n_contigs=2, contig_len=200, n_reads=400,
                            read_len=40, seed=52))
    lines = text.splitlines(keepends=True)
    # malformed body line (too few fields -> IndexError parity) spliced
    # near the middle, then another later — only the FIRST must surface
    mid = len(lines) // 2
    lines.insert(mid, "broken\tline\n")
    lines.insert(mid + 50, "also\tbroken\n")
    bad_text = "".join(lines)

    errs = []
    for n_threads in (1, 3):
        with pytest.raises(Exception) as ei:
            _decode(bad_text, n_threads, block_bytes=1024)
        errs.append((type(ei.value), str(ei.value)))
    assert errs[0] == errs[1]


def _run_cli_style(text, cfg):
    handle = io.StringIO(text)
    contigs, _n, first = read_header(handle)
    res = JaxBackend().run(contigs, ReadStream(handle, first), cfg)
    return {n: render_file(r, 0) for n, r in res.fastas.items()}


def test_backend_decode_threads_byte_identical():
    text = simulate(SimSpec(n_contigs=3, contig_len=250, n_reads=900,
                            read_len=50, ins_read_rate=0.25,
                            del_read_rate=0.15, seed=53))
    handle = io.StringIO(text)
    contigs, _n, first = read_header(handle)
    from sam2consensus_tpu.io.sam import iter_records
    res_cpu = CpuBackend().run(contigs, iter_records(handle, first),
                               RunConfig(prefix="t", thresholds=[0.25]))
    want = {n: render_file(r, 0) for n, r in res_cpu.fastas.items()}

    got = _run_cli_style(text, RunConfig(prefix="t", thresholds=[0.25],
                                         shards=1, decode_threads=3))
    assert got == want


# -- byte-shard rung --------------------------------------------------------
def _write(tmp_path, text, name="t.sam", mode="w"):
    path = tmp_path / name
    with open(path, mode) as fh:
        fh.write(text)
    return str(path)


def _decode_file(path, n_threads, min_bytes=1):
    """Decode a FILE via the decoder's rung selection (shard rung for
    plain files); returns (acc, dec, events, stream)."""
    from sam2consensus_tpu.encoder.parallel_decode import \
        ParallelFusedDecoder

    handle = opener(path, binary=True)
    contigs, _n, first = read_header(handle)
    layout = GenomeLayout(contigs)
    acc = HostPileupAccumulator(layout.total_len)
    dec = ParallelFusedDecoder(layout, acc.counts_host(), n_threads)
    stream = ReadStream(handle, first)
    events = 0
    try:
        for b in dec.encode_input(stream, min_shard_bytes=min_bytes):
            acc.add(b)
            events += b.n_events
    finally:
        handle.close()
    return acc, dec, events, stream


def _serial_reference(path):
    handle = opener(path, binary=True)
    contigs, _n, first = read_header(handle)
    layout = GenomeLayout(contigs)
    counts = np.zeros((layout.total_len, 6), dtype=np.int32)
    enc = NativeReadEncoder(layout, accumulate_into=counts)
    stream = ReadStream(handle, first)
    try:
        # encode_blocks_from stamps block_base per block (the backend's
        # serial path), so strict errors carry their absolute offset
        for _ in enc.encode_blocks_from(stream):
            pass
    finally:
        handle.close()
    return counts, enc, stream


def _assert_shard_equals_serial(path, n_threads, min_bytes=1):
    counts, senc, sstream = _serial_reference(path)
    acc, dec, _ev, pstream = _decode_file(path, n_threads,
                                          min_bytes=min_bytes)
    np.testing.assert_array_equal(counts, acc.counts_host())
    assert (senc.n_reads, senc.n_skipped) == (dec.n_reads, dec.n_skipped)
    assert len(senc.insertions) == len(dec.insertions)
    assert (sstream.n_lines, sstream.n_bytes) \
        == (pstream.n_lines, pstream.n_bytes)
    from sam2consensus_tpu.encoder.events import group_insertions
    g1 = group_insertions(senc.insertions, senc.layout)
    g2 = group_insertions(dec.insertions, dec.layout)
    assert (g1 is None) == (g2 is None)
    if g1 is not None:
        for k in g1:
            np.testing.assert_array_equal(g1[k], g2[k])


@pytest.mark.parametrize("n_threads", [2, 3, 8])
def test_shard_rung_equals_serial(tmp_path, n_threads):
    """min_bytes=1 forces one shard per thread, so every boundary falls
    mid-line and the snapping owns reads straddling the raw cuts."""
    text = simulate(SimSpec(n_contigs=4, contig_len=300, n_reads=1500,
                            read_len=60, ins_read_rate=0.2,
                            del_read_rate=0.2, seed=61))
    path = _write(tmp_path, text)
    _assert_shard_equals_serial(path, n_threads)


def test_shard_rung_direct_mode_equals_serial(tmp_path, monkeypatch):
    """Huge-genome counting mode (int32 direct, no shadow): workers use
    private int32 partitions merged at the end — forced onto a small
    genome via the fused-direct threshold knob."""
    monkeypatch.setenv("S2C_FUSED_DIRECT_MIN_LEN", "1")
    text = simulate(SimSpec(n_contigs=3, contig_len=300, n_reads=1000,
                            read_len=60, ins_read_rate=0.15,
                            del_read_rate=0.15, seed=71))
    path = _write(tmp_path, text)
    _assert_shard_equals_serial(path, 3)


def test_shard_rung_crlf_and_truncated_final_line(tmp_path):
    """CRLF terminators travel with their line through snapping, and an
    unterminated final line belongs to the last shard."""
    text = simulate(SimSpec(n_contigs=2, contig_len=200, n_reads=300,
                            read_len=40, seed=62))
    crlf = text.replace("\n", "\r\n")
    assert crlf.endswith("\r\n")
    truncated = crlf[:-2]          # drop the final terminator entirely
    path = _write(tmp_path, truncated)
    _assert_shard_equals_serial(path, 4)


def test_shard_rung_more_shards_than_records(tmp_path):
    """8 requested shards over 3 records: snapping collapses empty
    ranges and parity holds."""
    text = simulate(SimSpec(n_contigs=1, contig_len=120, n_reads=3,
                            read_len=30, seed=63))
    path = _write(tmp_path, text)
    _assert_shard_equals_serial(path, 8)


def test_shard_rung_single_record(tmp_path):
    text = simulate(SimSpec(n_contigs=1, contig_len=100, n_reads=1,
                            read_len=30, seed=64))
    path = _write(tmp_path, text)
    _assert_shard_equals_serial(path, 4)


def test_shard_rung_header_only(tmp_path):
    text = "@SQ\tSN:c1\tLN:100\n"
    path = _write(tmp_path, text)
    acc, dec, ev, _s = _decode_file(path, 3)
    assert dec.n_reads == 0 and ev == 0
    assert not acc.counts_host().any()


def test_shard_rung_error_is_first_bad_line(tmp_path):
    """Two bad lines in different shards: the earlier one's exception
    surfaces, with the serial path's exact type and message."""
    text = simulate(SimSpec(n_contigs=2, contig_len=200, n_reads=600,
                            read_len=40, seed=65))
    lines = text.splitlines(keepends=True)
    third = len(lines) // 3
    lines.insert(third, "broken\tline\n")
    lines.insert(2 * third, "also\tbroken\tbut\tlater\n")
    path = _write(tmp_path, "".join(lines))

    with pytest.raises(Exception) as serial_err:
        _serial_reference(path)
    errs = []
    for n_threads in (1, 4):
        with pytest.raises(Exception) as ei:
            _decode_file(path, n_threads)
        errs.append((type(ei.value), str(ei.value)))
    want = (type(serial_err.value), str(serial_err.value))
    assert errs == [want, want]


def test_plan_byte_shards_invariants():
    """Every line starts in exactly one range; ranges tile the span."""
    body = b"".join(b"line%d\tx\n" % i for i in range(200))
    data = b"@hdr\n" + body
    start = 5
    for n in (1, 2, 3, 7, 50, 500):
        ranges = ingest.plan_byte_shards(data, start, len(data), n,
                                         min_bytes=1)
        assert ranges[0][0] == start and ranges[-1][1] == len(data)
        for (a, b), (c, d) in zip(ranges, ranges[1:]):
            assert b == c
        for lo, hi in ranges:
            assert lo < hi
            # every range starts at a line start
            assert lo == start or data[lo - 1:lo] == b"\n"
        # the native one-pass snapper (s2c_snap_shards) and the python
        # fallback are semantics twins
        py = [start] + [
            ingest.snap_line_start(data, start + (len(data) - start) * k
                                   // n, start, len(data))
            for k in range(1, n)] + [len(data)]
        assert ingest._snap_bounds(data, start, len(data), n) == py


def test_gzip_falls_back_to_stream_rung(tmp_path):
    """Non-splittable gzip input: the streaming rung serves, counted as
    ingest/fallback, byte-identical output."""
    import gzip

    text = simulate(SimSpec(n_contigs=2, contig_len=200, n_reads=500,
                            read_len=40, ins_read_rate=0.1, seed=66))
    sam = _write(tmp_path, text)
    gz = str(tmp_path / "t.sam.gz")
    with gzip.open(gz, "wb") as fh:
        fh.write(text.encode())

    counts, _enc, _s = _serial_reference(sam)
    robs = observability.start_run()
    try:
        handle = opener(gz, binary=True)
        contigs, _n, first = read_header(handle)
        layout = GenomeLayout(contigs)
        acc = HostPileupAccumulator(layout.total_len)
        from sam2consensus_tpu.encoder.parallel_decode import \
            ParallelFusedDecoder

        dec = ParallelFusedDecoder(layout, acc.counts_host(), 2)
        for b in dec.encode_input(ReadStream(handle, first)):
            acc.add(b)
        handle.close()
        snap = observability.metrics().snapshot()
        assert snap["counters"].get("ingest/fallback") == 1
        mode = snap["gauges"]["ingest/mode"]["info"]
        assert mode["rung"] == "stream"
    finally:
        observability.finish_run(robs)
    np.testing.assert_array_equal(counts, acc.counts_host())


def test_shard_fault_retries_once_then_succeeds(tmp_path):
    """An injected ingest_decode_shard fault costs one retry; counts
    stay exact and the retry is counted."""
    from sam2consensus_tpu.resilience import faultinject

    text = simulate(SimSpec(n_contigs=2, contig_len=200, n_reads=800,
                            read_len=40, ins_read_rate=0.1, seed=67))
    path = _write(tmp_path, text)
    counts, senc, _s = _serial_reference(path)

    robs = observability.start_run()
    faultinject.configure("ingest_decode_shard:rpc:0")
    try:
        acc, dec, _ev, _st = _decode_file(path, 2)
        snap = observability.metrics().snapshot()
        assert snap["counters"].get("ingest/shard_retries") == 1
        assert "ingest/demoted" not in snap["counters"]
    finally:
        faultinject.configure("")
        observability.finish_run(robs)
    np.testing.assert_array_equal(counts, acc.counts_host())
    assert dec.n_reads == senc.n_reads


def test_shard_fault_persistent_demotes_to_serial(tmp_path):
    """A persistent fault demotes the WHOLE ingest to the serial rung:
    counts exact (never corrupted by partial shard work), demotion
    counted."""
    from sam2consensus_tpu.resilience import faultinject

    text = simulate(SimSpec(n_contigs=2, contig_len=200, n_reads=800,
                            read_len=40, ins_read_rate=0.1, seed=68))
    path = _write(tmp_path, text)
    counts, senc, _s = _serial_reference(path)

    robs = observability.start_run()
    faultinject.configure("ingest_decode_shard:rpc:0:inf")
    try:
        acc, dec, _ev, _st = _decode_file(path, 2)
        snap = observability.metrics().snapshot()
        assert snap["counters"].get("ingest/demoted") == 1
        assert snap["counters"].get("ingest/shard_retries", 0) >= 1
    finally:
        faultinject.configure("")
        observability.finish_run(robs)
    np.testing.assert_array_equal(counts, acc.counts_host())
    assert dec.n_reads == senc.n_reads
    assert len(dec.insertions) == len(senc.insertions)


def test_backend_file_shard_rung_byte_identical(tmp_path):
    """End-to-end through the jax backend over a real file (the shard
    rung engages, unlike the in-memory StringIO test above), fused host
    path AND the slab/device path, vs the CPU oracle."""
    text = simulate(SimSpec(n_contigs=3, contig_len=250, n_reads=1200,
                            read_len=50, ins_read_rate=0.25,
                            del_read_rate=0.15, seed=69))
    path = _write(tmp_path, text)

    from sam2consensus_tpu.io.sam import read_sam
    contigs, records = read_sam(path)
    res_cpu = CpuBackend().run(contigs, records,
                               RunConfig(prefix="t", thresholds=[0.25]))
    want = {n: render_file(r, 0) for n, r in res_cpu.fastas.items()}

    for extra in ({}, {"pileup": "scatter"}):
        with open(path, "rb") as fh:
            contigs, _n, first = read_header(fh)
            res = JaxBackend().run(
                contigs, ReadStream(fh, first),
                RunConfig(prefix="t", thresholds=[0.25], shards=1,
                          decode_threads=2, **extra))
        got = {n: render_file(r, 0) for n, r in res.fastas.items()}
        assert got == want, f"mismatch for {extra}"
        assert res.stats.extra.get("ingest/shards", 0) >= 1


def test_decode_threads_decision_in_manifest(tmp_path):
    """--decode-threads is a priced, recorded decision: it lands in the
    run manifest with its inputs and a residual joined against the
    realized phase/decode_sec.  The fused host rung keeps the enforced
    drift band (decode wall == decode work there); the slab/device rung
    is informational (band=0) because the pipeline's whole point is
    hiding decode wall under dispatch."""
    text = simulate(SimSpec(n_contigs=2, contig_len=250, n_reads=900,
                            read_len=50, seed=72))
    path = _write(tmp_path, text)

    def _run(**extra):
        with open(path, "rb") as fh:
            contigs, _n, first = read_header(fh)
            JaxBackend().run(contigs, ReadStream(fh, first),
                             RunConfig(prefix="t", thresholds=[0.25],
                                       shards=1, decode_threads=2,
                                       **extra))
        man = observability.last_manifest()
        assert man is not None
        return {d["decision"]: d for d in man["decisions"]}

    dec = _run()["decode_threads"]                     # fused host rung
    assert dec["chosen"] == "2"
    assert dec["inputs"]["rung"] == "fused"
    assert dec["inputs"]["parallel"] is True
    assert dec["predicted"].get("sec", 0) > 0
    assert "sec" in dec["residual"]

    dec = _run(pileup="scatter")["decode_threads"]     # slab rung
    assert dec["inputs"]["rung"] == "slab"
    assert "sec" in dec["residual"]
    assert not dec["drift"]      # informational on the pipelined rung


def test_shared_ingest_pool_grows_and_survives_close(tmp_path):
    """BGZF readers ride the process-wide ingest pool: closing one
    reader must not tear the pool down for others."""
    from sam2consensus_tpu.formats.bgzf import BgzfReader, write_bgzf

    text = simulate(SimSpec(n_contigs=1, contig_len=200, n_reads=2000,
                            read_len=40, seed=70))
    path = str(tmp_path / "t.sam.gz")
    write_bgzf(text.encode(), path)

    r1 = BgzfReader(path, threads=2)
    r2 = BgzfReader(path, threads=2)
    assert r1._pool is r2._pool
    first = r1.read(100)
    r1.close()
    # growing the pool mid-read (a later open with a larger budget
    # retires the old executor) must not break readers already open:
    # submits go through ingest.pool_submit, never a cached executor
    r3 = BgzfReader(path, threads=4)
    out = r2.read()
    r2.close()
    out3 = r3.read()
    r3.close()
    assert first == text.encode()[:100]
    assert out == text.encode()
    assert out3 == text.encode()
    assert ingest.pool_info()["workers"] >= 4


# -- strict first-error offset parity (ISSUE 9) -----------------------------
def _strict_outcome(path, n_threads=None):
    """(type, message, s2c_offset) of the strict first error — serial
    rung when n_threads is None, else the decoder's rung selection
    (shard for plain files, stream for gzip)."""
    try:
        if n_threads is None:
            _serial_reference(path)
        else:
            _decode_file(path, n_threads)
    except Exception as exc:  # noqa: BLE001 - the outcome IS the assert
        return (type(exc).__name__, str(exc),
                getattr(exc, "s2c_offset", None))
    raise AssertionError("strict decode accepted the corrupt input")


def test_strict_error_offset_parity_across_rungs(tmp_path):
    """The first bad record's ABSOLUTE file offset rides the exception
    (``s2c_offset``) identically on the serial, byte-shard and
    streaming-gzip rungs."""
    import gzip as _gzip

    text = simulate(SimSpec(n_contigs=2, contig_len=250, n_reads=700,
                            read_len=50, seed=91))
    lines = text.splitlines(keepends=True)
    body = [i for i, ln in enumerate(lines) if not ln.startswith("@")]
    bad = "corrupt\trecord\n"
    lines.insert(body[len(body) // 2], bad)
    dirty = "".join(lines)
    want_off = dirty.index(bad)

    sam = _write(tmp_path, dirty)
    gz = str(tmp_path / "t.sam.gz")
    with _gzip.open(gz, "wb") as fh:
        fh.write(dirty.encode("ascii"))

    serial = _strict_outcome(sam)
    assert serial[2] == want_off, "serial rung offset is the anchor"
    for n in (2, 3, 8):
        assert _strict_outcome(sam, n) == serial, f"shard rung x{n}"
    assert _strict_outcome(gz, 2) == serial, "streaming rung"


def test_strict_error_offset_snap_straddling_line(tmp_path):
    """A corrupt line that CONTAINS the raw byte cut: snapping assigns
    the whole line to the earlier shard, and the reported offset must
    still be the line's absolute start — exactly what the serial rung
    says, for every thread count that puts a cut inside it."""
    text = simulate(SimSpec(n_contigs=1, contig_len=400, n_reads=400,
                            read_len=80, seed=92))
    lines = text.splitlines(keepends=True)
    # locate the line containing the 2-way raw midpoint cut
    data_len = len(text.encode("ascii"))
    mid = data_len // 2
    pos = 0
    target = None
    for i, ln in enumerate(lines):
        if pos <= mid < pos + len(ln) and not ln.startswith("@"):
            target = i
            break
        pos += len(ln)
    assert target is not None
    # same-length corruption (POS digits -> 'x's) so the cut math is
    # unchanged and the bad line still straddles the boundary
    f = lines[target].split("\t")
    f[3] = "x" * len(f[3])
    lines[target] = "\t".join(f)
    dirty = "".join(lines)
    want_off = sum(len(ln) for ln in lines[:target])

    sam = _write(tmp_path, dirty)
    serial = _strict_outcome(sam)
    assert serial[2] == want_off
    for n in (2, 3, 5):
        assert _strict_outcome(sam, n) == serial, f"straddle x{n}"


def test_strict_error_message_parity_bam_vs_text(tmp_path):
    """A semantically-bad record (out-of-bounds span) raises the
    oracle's EXACT type+message through the text rungs AND both BAM
    decode lanes (native C and the pure-python twin) — offsets are
    format-local, so the parity contract there is type+message."""
    from sam2consensus_tpu.formats import open_alignment_input
    from sam2consensus_tpu.formats.bam import sam_text_to_bam

    text = ("@SQ\tSN:c1\tLN:100\n"
            "good\t0\tc1\t1\t60\t4M\t*\t0\t0\tACGT\t*\n"
            "oob\t0\tc1\t99\t60\t8M\t*\t0\t0\tACGTACGT\t*\n")
    sam = _write(tmp_path, text)
    serial = _strict_outcome(sam)
    assert serial[2] == text.index("oob\t0")

    bam = str(tmp_path / "t.bam")
    sam_text_to_bam(text, bam)
    outs = {}
    for decoder in ("native", "py"):
        ai = open_alignment_input(bam, "bam")
        layout = GenomeLayout(ai.contigs)
        enc, batches = ai.stream.make_encoder(
            layout, RunConfig(prefix="x", decoder=decoder))
        try:
            with pytest.raises(Exception) as ei:
                for _b in batches:
                    pass
            outs[decoder] = (type(ei.value).__name__, str(ei.value))
        finally:
            ai.close()
    assert outs["native"] == outs["py"] == serial[:2]
