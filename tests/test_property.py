"""Property-based tests (SURVEY.md §4): random read sets → invariants.

Each property runs the full pipeline (or the relevant slice) over
Hypothesis-generated SAM inputs that respect the input contract (§2 quirk
7: uppercase ACGTN plus literal '-', reads within wrap bounds, SEQ length
consistent with CIGAR):

* CPU oracle and JAX backend produce byte-identical FASTA;
* the native decoder agrees with the Python encoder;
* output is invariant under read-order permutation (addition commutes);
* the vmapped multi-threshold vote equals per-threshold votes;
* the sharded accumulator equals the single-device accumulator.
"""

import io
import random

import numpy as np
import pytest

# hypothesis is an optional [dev] extra (pyproject.toml): collection
# must skip, not error, on environments without it
pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from sam2consensus_tpu.backends.cpu import CpuBackend
from sam2consensus_tpu.backends.jax_backend import JaxBackend
from sam2consensus_tpu.config import RunConfig
from sam2consensus_tpu.io.fasta import render_file
from sam2consensus_tpu.io.sam import ReadStream, iter_records, read_header
from sam2consensus_tpu.utils.simulate import sam_text

SETTINGS = settings(max_examples=25, deadline=None)


@st.composite
def sam_inputs(draw):
    n_contigs = draw(st.integers(1, 3))
    contigs = [(f"c{i}", draw(st.integers(1, 40)))
               for i in range(n_contigs)]
    reads = []
    for _ in range(draw(st.integers(0, 10))):
        ci = draw(st.integers(0, n_contigs - 1))
        name, length = contigs[ci]
        ops = []
        span = 0
        read_len = 0
        for _ in range(draw(st.integers(1, 5))):
            op = draw(st.sampled_from("MIDNSHP=XI"))
            ln = draw(st.integers(1, 6))
            if op in "M=X":
                span += ln
                read_len += ln
            elif op in "DNP":
                span += ln
            elif op in "IS":
                read_len += ln
            ops.append(f"{ln}{op}")
        if span > 2 * length:
            continue  # no in-bounds placement exists for this CIGAR
        # 0-based pos in [-length, length - span] (negative wraps allowed)
        pos0 = draw(st.integers(-length, length - span))
        seq = "".join(draw(st.lists(
            st.sampled_from("ACGTN-"), min_size=read_len,
            max_size=read_len)))
        reads.append((name, pos0 + 1, "".join(ops), seq))
    cfg = dict(
        thresholds=draw(st.lists(
            st.floats(0.01, 1.0, allow_nan=False), min_size=1, max_size=3)),
        min_depth=draw(st.integers(1, 3)),
        fill=draw(st.sampled_from("-N?")),
        maxdel=draw(st.sampled_from([None, 0, 3, 150])),
    )
    return contigs, reads, cfg


def _render(backend, text, cfg):
    handle = io.StringIO(text)
    contigs, _n, first = read_header(handle)
    res = backend.run(contigs, ReadStream(handle, first), cfg)
    return {n: render_file(r, 0) for n, r in res.fastas.items()}


@SETTINGS
@given(sam_inputs())
def test_cpu_jax_byte_identity(inp):
    contigs, reads, cfg_kw = inp
    text = sam_text(contigs, reads)
    cfg_cpu = RunConfig(prefix="h", **cfg_kw)
    cfg_jax = RunConfig(prefix="h", backend="jax", decoder="py",
                        **cfg_kw)
    assert _render(JaxBackend(), text, cfg_jax) == \
        _render(CpuBackend(), text, cfg_cpu)


@SETTINGS
@given(sam_inputs())
def test_native_decoder_matches_python(inp):
    from sam2consensus_tpu.encoder import native_encoder

    if not native_encoder.available():
        pytest.skip("C++ decoder unavailable")
    contigs, reads, cfg_kw = inp
    text = sam_text(contigs, reads)
    cfg_py = RunConfig(prefix="h", backend="jax", decoder="py", **cfg_kw)
    cfg_nat = RunConfig(prefix="h", backend="jax", decoder="native",
                        **cfg_kw)
    assert _render(JaxBackend(), text, cfg_nat) == \
        _render(JaxBackend(), text, cfg_py)


@SETTINGS
@given(sam_inputs(), st.randoms())
def test_read_order_permutation_invariant(inp, rng):
    contigs, reads, cfg_kw = inp
    shuffled = list(reads)
    rng.shuffle(shuffled)
    cfg = RunConfig(prefix="h", backend="jax", decoder="py", **cfg_kw)
    assert _render(JaxBackend(), sam_text(contigs, shuffled), cfg) == \
        _render(JaxBackend(), sam_text(contigs, reads), cfg)


@SETTINGS
@given(sam_inputs())
def test_vmap_thresholds_equals_looped(inp):
    contigs, reads, cfg_kw = inp
    text = sam_text(contigs, reads)
    multi = RunConfig(prefix="h", backend="jax", decoder="py", **cfg_kw)
    combined = _render(JaxBackend(), text, multi)
    looped = {}
    for t in cfg_kw["thresholds"]:
        one = dict(cfg_kw, thresholds=[t])
        for name, body in _render(
                JaxBackend(), text, RunConfig(prefix="h", backend="jax",
                                              decoder="py", **one)).items():
            looped[name] = looped.get(name, "") + body
    assert combined == looped


@settings(max_examples=10, deadline=None)
@given(sam_inputs())
def test_sharded_counts_equal_unsharded(inp):
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs multiple devices")
    contigs, reads, cfg_kw = inp
    text = sam_text(contigs, reads)
    cfg1 = RunConfig(prefix="h", backend="jax", decoder="py", shards=1,
                     **cfg_kw)
    cfg8 = RunConfig(prefix="h", backend="jax", decoder="py",
                     shards=len(jax.devices()), **cfg_kw)
    assert _render(JaxBackend(), text, cfg8) == \
        _render(JaxBackend(), text, cfg1)


@settings(max_examples=40, deadline=None)
@given(
    t=st.floats(min_value=1e-12, max_value=4.0, allow_nan=False,
                allow_infinity=False),
    covs=st.lists(st.integers(min_value=0, max_value=2 ** 31 - 1),
                  min_size=1, max_size=64))
def test_exact_cutoff_matches_float64_oracle(t, covs):
    """Device int32-limb cutoff == ceil(numpy float64 product), any double
    threshold, any int32 coverage (the reference's float compare,
    sam2consensus.py:359-367)."""
    import jax
    import jax.numpy as jnp

    from sam2consensus_tpu.ops.cutoff import encode_thresholds, exact_cutoff

    cov = np.asarray(covs, dtype=np.int32)
    enc = encode_thresholds([t])
    got = np.asarray(jax.jit(exact_cutoff)(jnp.asarray(cov),
                                           jnp.asarray(enc[0])))
    want = np.minimum(np.ceil(np.float64(t) * cov.astype(np.float64)),
                      2 ** 31 - 1).astype(np.int64)
    np.testing.assert_array_equal(got.astype(np.int64), want)


@settings(max_examples=30, deadline=None)
@given(
    codes=st.lists(st.integers(min_value=0, max_value=31),
                   min_size=1, max_size=300),
    n_thr=st.integers(min_value=1, max_value=3))
def test_packed5_roundtrip(codes, n_thr):
    """Device 5-bit plane packing -> host expansion is the identity over
    every code value and any (length, threshold-count) shape, including
    odd lengths and non-multiple-of-8 tails."""
    import jax.numpy as jnp

    from sam2consensus_tpu.backends.jax_backend import JaxBackend
    from sam2consensus_tpu.constants import SYM32_ASCII
    from sam2consensus_tpu.ops.fused import _pack5_planes

    code5 = np.asarray(codes, dtype=np.uint8)[None, :].repeat(n_thr, 0)
    # distinct per-threshold rows: shift each row's codes mod 32
    code5 = (code5 + np.arange(n_thr, dtype=np.uint8)[:, None]) % 32
    nibs, hbits = _pack5_planes(jnp.asarray(code5))
    buf = np.concatenate([np.asarray(nibs).reshape(-1),
                          np.asarray(hbits).reshape(-1),
                          np.zeros(8, np.uint8)])
    syms, used = JaxBackend._expand_packed5(buf, n_thr, len(codes))
    want = SYM32_ASCII[code5]
    np.testing.assert_array_equal(syms, want)
    assert used == n_thr * ((len(codes) + 1) // 2 + (len(codes) + 7) // 8)


@settings(max_examples=25, deadline=None)
@given(
    counts=st.lists(
        st.lists(st.integers(min_value=0, max_value=5000),
                 min_size=6, max_size=6),
        min_size=1, max_size=200),
    t=st.floats(min_value=1e-9, max_value=1.5, allow_nan=False,
                allow_infinity=False),
    min_depth=st.sampled_from([0, 1, 2, 7]))
def test_native_vote_matches_device_vote(counts, t, min_depth):
    """The C++ tail vote == the device vote over arbitrary count tensors,
    thresholds and min_depth (both pinned to the oracle's greedy walk
    elsewhere; this pins them to each other under hypothesis).  Counts
    pad to a fixed length so the jitted device vote compiles once per
    min_depth instead of once per example (pad rows have cov 0 -> the
    sentinel on both sides)."""
    import jax.numpy as jnp

    from sam2consensus_tpu import native
    from sam2consensus_tpu.ops.cutoff import encode_thresholds
    from sam2consensus_tpu.ops.vote import (vote_positions,
                                            vote_positions_native)

    if native.load() is None:
        pytest.skip("native library unavailable")
    arr = np.zeros((256, 6), dtype=np.int32)
    arr[:len(counts)] = np.asarray(counts, dtype=np.int32)
    got = vote_positions_native(arr, [t], min_depth)
    want_syms, want_cov = vote_positions(
        jnp.asarray(arr), jnp.asarray(encode_thresholds([t])), min_depth)
    np.testing.assert_array_equal(got[0], np.asarray(want_syms))
    np.testing.assert_array_equal(got[1], np.asarray(want_cov))
