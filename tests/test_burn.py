"""Multi-window SLO burn alerting (observability/burn.py) + the
windowed metric rings behind it (metrics.Windowed).

Covers (ISSUE 19):
* windowed ring views on the metrics registry;
* burn rates over fast/slow windows, with caller-supplied stamps so
  fleet-replayed breaches age like local ones;
* the ok -> warn -> page state machine with hysteresis: a single blip
  never alarms, a sustained breach pages, recovery de-escalates one
  level per quiet period;
* ``burn_counts`` — the decaying replacement for the admission
  controller's never-decaying ``slo_burn_by_tenant`` reads — and the
  ``AdmissionController.slo_burn()`` routing;
* ``replay_burn`` hindsight verdicts over journal events (the
  tools/fleet_whatif.py scorer);
* the s2c_burn_* exposition families.
"""

from sam2consensus_tpu.observability import burn as B
from sam2consensus_tpu.observability import telemetry as T
from sam2consensus_tpu.observability.metrics import (MetricsRegistry,
                                                     WINDOW_CAP,
                                                     Windowed)
from sam2consensus_tpu.serve.admission import AdmissionController


# =========================================================================
# units: windowed rings
# =========================================================================
def test_windowed_ring_filters_by_stamp():
    w = Windowed()
    for i in range(10):
        w.observe(float(i), stamp=100.0 + i)
    assert w.window(5.0, now=109.0) == [4.0, 5.0, 6.0, 7.0, 8.0, 9.0]
    assert w.window(100.0, now=109.0) == [float(i) for i in range(10)]
    assert w.window(5.0, now=500.0) == []          # all aged out


def test_windowed_ring_overwrites_past_cap():
    w = Windowed()
    for i in range(WINDOW_CAP + 10):
        w.observe(1.0, stamp=float(i))
    assert w.count == WINDOW_CAP + 10
    vals = w.window(float(WINDOW_CAP + 10), now=float(WINDOW_CAP + 9))
    assert len(vals) == WINDOW_CAP                 # ring, not a leak


def test_registry_window_values():
    reg = MetricsRegistry()
    reg.observe("burn/t/violated", 1.0, stamp=100.0)
    reg.observe("burn/t/violated", 1.0, stamp=200.0)
    assert reg.window_values("burn/t/violated", 50.0, now=210.0) \
        == [1.0]
    assert reg.window_values("burn/t/violated", 150.0, now=210.0) \
        == [1.0, 1.0]
    assert reg.window_values("burn/absent", 50.0, now=210.0) == []


# =========================================================================
# burn rates + state machine
# =========================================================================
def _mon(reg=None, **kw):
    kw.setdefault("fast_sec", 300.0)
    kw.setdefault("slow_sec", 3600.0)
    kw.setdefault("warn_ratio", 0.25)
    kw.setdefault("page_ratio", 0.5)
    kw.setdefault("min_violations", 2)
    kw.setdefault("clear_sec", 300.0)
    return B.BurnMonitor(reg if reg is not None else MetricsRegistry(),
                         **kw)


def test_single_blip_stays_ok():
    mon = _mon()
    t0 = 10_000.0
    # one violated job in an otherwise empty window: ratio 1.0 but
    # below min_violations — the classic false-page this gate kills
    mon.observe_job("ta", evaluated=1, violated=1, now=t0)
    assert mon.tick(t0 + 1) == {"ta": "ok"}


def test_sustained_breach_pages_and_clean_tenant_stays_ok():
    mon = _mon()
    t0 = 10_000.0
    for i in range(4):
        mon.observe_job("hung", evaluated=1, violated=1, now=t0 + i)
        mon.observe_job("fine", evaluated=1, violated=0, now=t0 + i)
    states = mon.tick(t0 + 10)
    assert states["hung"] == "page"      # burning in BOTH windows
    assert states["fine"] == "ok"
    assert mon.rate("hung", "fast", now=t0 + 10) == 1.0
    assert mon.rate("fine", "slow", now=t0 + 10) == 0.0


def test_warn_without_page_when_slow_window_healthy():
    mon = _mon(fast_sec=60.0, slow_sec=3600.0)
    t0 = 50_000.0
    # an hour of clean traffic, then a fresh fast-window burn: fast
    # ratio 1.0 but the slow ratio is diluted below page_ratio
    for i in range(20):
        mon.observe_job("ta", evaluated=1, violated=0,
                        now=t0 - 3000.0 + i)
    for i in range(3):
        mon.observe_job("ta", evaluated=1, violated=1, now=t0 + i)
    assert mon.tick(t0 + 5) == {"ta": "warn"}


def test_recovery_deescalates_one_level_per_quiet_period():
    mon = _mon(clear_sec=300.0)
    t0 = 10_000.0
    for i in range(4):
        mon.observe_job("ta", evaluated=1, violated=1, now=t0 + i)
    assert mon.tick(t0 + 5) == {"ta": "page"}
    # fast window clears as the breaches age out; hysteresis steps
    # page -> warn -> ok, one level per clear_sec of quiet
    assert mon.tick(t0 + 400) == {"ta": "warn"}
    assert mon.tick(t0 + 500) == {"ta": "warn"}   # quiet < clear_sec
    assert mon.tick(t0 + 800) == {"ta": "ok"}


def test_flapping_does_not_oscillate_to_page():
    mon = _mon(fast_sec=300.0, clear_sec=300.0)
    t0 = 10_000.0
    for i in range(4):
        mon.observe_job("ta", evaluated=1, violated=1, now=t0 + i)
    assert mon.tick(t0 + 5) == {"ta": "page"}
    # a fresh blip during recovery re-arms last_above: the state
    # holds (escalation is only ever upward from the current level)
    assert mon.tick(t0 + 400) == {"ta": "warn"}
    mon.observe_job("ta", evaluated=1, violated=1, now=t0 + 410)
    assert mon.tick(t0 + 420) == {"ta": "warn"}   # blip: min_violations
    assert mon.tick(t0 + 1020) == {"ta": "ok"}


# =========================================================================
# burn_counts: the decaying slo_burn_by_tenant replacement
# =========================================================================
def test_burn_counts_decay_out_of_window():
    mon = _mon(slow_sec=3600.0)
    t0 = 100_000.0
    mon.observe_job("ta", evaluated=1, violated=1, now=t0)
    mon.observe_job("tb", evaluated=1, violated=0, now=t0)
    assert mon.burn_counts("slow", now=t0 + 10) == {"ta": 1}
    # an hour later the breach has aged out: ta reads UNBURNT — the
    # exact read the lifetime dict could never produce
    assert mon.burn_counts("slow", now=t0 + 3700.0) == {}


def test_admission_slo_burn_routes_through_monitor():
    adm = AdmissionController()
    adm.note_slo("ta", 1)
    assert adm.slo_burn() == {"ta": 1}            # no monitor: dict
    mon = _mon()
    adm.burn_monitor = mon
    t0 = 100_000.0
    mon.observe_job("ta", evaluated=1, violated=1, now=t0)
    assert adm.slo_burn(now=t0 + 10) == {"ta": 1}
    # the monitor is the truth for tenants it has seen: the aged-out
    # breach decays even though the lifetime dict still says 1
    assert adm.slo_burn(now=t0 + 9999.0) == {}
    assert adm.slo_burn_by_tenant == {"ta": 1}    # dict untouched
    # dict entries for tenants the monitor never saw pass through
    # (tests/tools seed burn directly)
    adm.slo_burn_by_tenant["hot"] = 2
    assert adm.slo_burn(now=t0 + 9999.0) == {"hot": 2}


# =========================================================================
# replay_burn: the whatif scorer
# =========================================================================
def test_replay_burn_pages_exactly_the_hung_tenant():
    t0 = 200_000.0
    events = []
    for i in range(6):
        events.append({"ev": "committed", "t": t0 + i,
                       "tenant": "hung", "elapsed_sec": 9.0})
        events.append({"ev": "committed", "t": t0 + i,
                       "tenant": "fine", "elapsed_sec": 0.2})
    events.append({"ev": "submitted", "t": t0, "tenant": "hung"})
    out = B.replay_burn(events, {"e2e": 2.0}, min_violations=2)
    assert out["states"]["hung"] == "page"
    assert out["states"]["fine"] == "ok"
    snap = out["snapshot"]
    assert snap["tenants"]["hung"]["fast"]["ratio"] == 1.0
    assert snap["tenants"]["fine"]["slow"]["violated"] == 0


def test_replay_burn_old_breaches_read_ok_now():
    t0 = 200_000.0
    events = [{"ev": "committed", "t": t0 + i, "tenant": "ta",
               "elapsed_sec": 9.0} for i in range(4)]
    # scored AT the breach time: paging
    assert B.replay_burn(events, {"e2e": 2.0})["states"]["ta"] \
        == "page"
    # scored two hours later: every breach aged out of both windows
    assert B.replay_burn(events, {"e2e": 2.0},
                         now=t0 + 7200.0)["states"]["ta"] == "ok"


def test_replay_burn_no_objective_is_quiet():
    events = [{"ev": "committed", "t": 1.0, "tenant": "ta",
               "elapsed_sec": 9.0}]
    assert B.replay_burn(events, {})["states"] == {}
    assert B.replay_burn(events, None)["states"] == {}


# =========================================================================
# exposition
# =========================================================================
def test_burn_families_render_and_lint():
    reg = MetricsRegistry()
    mon = _mon(reg)
    t0 = 10_000.0
    for i in range(4):
        mon.observe_job("ta", evaluated=1, violated=1, now=t0 + i)
    mon.tick(t0 + 5)
    reg.gauge("process/start_time_seconds").set(t0)
    text = T.render_openmetrics(reg.snapshot(), worker="w0",
                                restart_epoch=0)
    assert ('s2c_burn_rate{tenant="ta",window="fast",worker="w0",'
            'restart_epoch="0"} 1') in text
    assert 's2c_burn_rate{tenant="ta",window="slow"' in text
    assert ('s2c_burn_alert_state{tenant="ta",worker="w0",'
            'restart_epoch="0"} 2') in text
    # the raw windowed rings are internal state, not families
    assert "s2c_burn_ta" not in text
    assert T.lint_openmetrics(text) == []
