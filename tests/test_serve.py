"""Serve-mode correctness: warm jobs ARE cold jobs, plus the warm wins.

The PR-5 acceptance pins live here:

* N jobs submitted warm are byte-identical to N independent cold runs —
  including a gzip-compressed input and a ``--py2-compat`` job;
* ``compile/jit_cache_hit`` > 0 on job 2+ with zero re-trace (and zero
  re-trace on job 1 for prewarmed shapes);
* a mid-queue injected device fault demotes ONLY the faulting job's
  ladder (counter-pinned): the next job runs on the fast path, warm;
* ``serve/overlap_sec`` is published per job and the thread-scoped
  observability binding keeps concurrent registries isolated.
"""

import json
import os
import subprocess
import sys
import threading

import pytest

from sam2consensus_tpu.config import RunConfig
from sam2consensus_tpu.io.fasta import render_file
from sam2consensus_tpu.io.sam import ReadStream, opener, read_header
from sam2consensus_tpu.utils.simulate import SimSpec, simulate

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_persistent_cache(monkeypatch):
    # keep the process-global jax compilation-cache config untouched
    # across the suite; the persistent cache gets its own subprocess
    # test below
    monkeypatch.setenv("S2C_JIT_CACHE", "")


def _sim(tmp, name, seed, contig_len=3000, n_reads=1200, gz=False,
         **kw):
    spec = SimSpec(n_contigs=1, contig_len=contig_len, n_reads=n_reads,
                   read_len=100, contig_len_jitter=0.0, seed=seed,
                   contig_prefix="srv", **kw)
    path = os.path.join(str(tmp), name)
    text = simulate(spec)
    if gz:
        import gzip

        with gzip.open(path, "wb") as fh:
            fh.write(text.encode("ascii"))
    else:
        with open(path, "w") as fh:
            fh.write(text)
    return path


def _cold_jax(path, cfg):
    """One independent cold run (fresh backend), rendered."""
    from sam2consensus_tpu.backends.jax_backend import JaxBackend

    h = opener(path, binary=True)
    contigs, _n, first = read_header(h)
    res = JaxBackend().run(contigs, ReadStream(h, first), cfg)
    h.close()
    return {n: render_file(r, 0) for n, r in res.fastas.items()}, res


def _rendered(result):
    return {n: render_file(r, 0) for n, r in result.fastas.items()}


def _runner(**kw):
    from sam2consensus_tpu.serve import ServeRunner

    kw.setdefault("prewarm", "off")
    kw.setdefault("persistent_cache", False)
    return ServeRunner(**kw)


# -- thread-scoped observability ------------------------------------------
def test_bind_thread_registry_isolation():
    from sam2consensus_tpu import observability as obs
    from sam2consensus_tpu.observability.metrics import current

    robs = obs.prepare_run()
    seen = {}

    def side():
        with obs.bind_run_to_thread(robs):
            current().add("x/side", 1)
            seen["side"] = current() is robs.registry
        seen["after"] = current() is robs.registry

    t = threading.Thread(target=side)
    t.start()
    t.join()
    assert seen == {"side": True, "after": False}
    assert robs.registry.value("x/side") == 1
    # the main thread never saw the bound registry
    assert current() is not robs.registry


def test_intersect_sec_cross_lists():
    from sam2consensus_tpu.wire.pipeline import intersect_sec

    a = [(0.0, 1.0), (2.0, 3.0)]
    b = [(0.5, 2.5)]
    assert intersect_sec(a, b) == pytest.approx(1.0)
    assert intersect_sec([], b) == 0.0


# -- prewarm enumeration ---------------------------------------------------
def test_canonical_slab_shapes_cover_hint():
    from sam2consensus_tpu.ops.pileup import canonical_slab_shapes

    shapes = canonical_slab_shapes(5386, read_len=100, n_reads=3000)
    assert (4096, 256) in shapes        # the measured sim shape
    assert all(w in (128, 256) for _r, w in shapes)
    # server-startup enumeration covers every pow2 level >= 1024
    full = canonical_slab_shapes(5386, read_len=100)
    assert (4096, 256) in full and (1024, 128) in full
    assert len(full) < 20               # a handful, not a sweep


def test_prewarm_scatter_compiles_without_counting():
    import numpy as np

    from sam2consensus_tpu.observability.metrics import (pop_run,
                                                         push_run)
    from sam2consensus_tpu.ops.pileup import (PileupAccumulator,
                                              prewarm_scatter)

    reg = push_run()
    try:
        assert prewarm_scatter(911, [(64, 32)]) == 1
        assert reg.value("compile/trace/scatter_packed/64x32") == 1
        # a matching dispatch afterwards is a pure hit, and counts only
        # what its rows say
        acc = PileupAccumulator(911, strategy="scatter")
        from sam2consensus_tpu.encoder.events import SegmentBatch

        starts = np.zeros(64, np.int32)
        codes = np.full((64, 32), 255, np.uint8)
        codes[:, 0] = 1                 # all rows real: no pad-trim,
        acc.add(SegmentBatch(buckets={32: (starts, codes)}))  # 64x32
        assert reg.value("compile/jit_cache_hit") == 1
        assert reg.value("compile/jit_cache_miss") == 0
        assert int(np.asarray(acc.counts_host()).sum()) == 64
    finally:
        pop_run(reg)


# -- warm-vs-cold byte identity -------------------------------------------
def test_warm_jobs_byte_identical_to_cold(tmp_path):
    from sam2consensus_tpu.serve import JobSpec

    jobs = [
        (_sim(tmp_path, "a.sam", 11),
         RunConfig(backend="jax", pileup="scatter", shards=1, prefix="a")),
        (_sim(tmp_path, "b.sam.gz", 12, gz=True),
         RunConfig(backend="jax", pileup="scatter", shards=1, prefix="b")),
        (_sim(tmp_path, "c.sam", 13),
         RunConfig(backend="jax", pileup="scatter", shards=1, prefix="c",
                   py2_compat=True, maxdel=None)),
        (_sim(tmp_path, "d.sam", 14),
         RunConfig(backend="jax", pileup="scatter", shards=1, prefix="d",
                   thresholds=[0.25, 0.75])),
    ]
    runner = _runner()
    results = runner.submit_jobs(
        [JobSpec(filename=p, config=c) for p, c in jobs])
    assert [r.ok for r in results] == [True] * len(jobs)
    for (path, cfg), res in zip(jobs, results):
        cold, cold_res = _cold_jax(path, cfg)
        assert _rendered(res) == cold, f"warm != cold for {path}"
        assert res.stats.reads_mapped == cold_res.stats.reads_mapped
    # cross-check one job against the CPU golden oracle too
    from sam2consensus_tpu.backends.cpu import CpuBackend

    path, cfg = jobs[0]
    h = opener(path, binary=False)
    contigs, _n, first = read_header(h)
    oracle = CpuBackend().run(contigs, ReadStream(h, first), cfg)
    h.close()
    assert _rendered(results[0]) == _rendered(oracle)


# -- jit-cache amortization ------------------------------------------------
def test_jit_cache_hit_on_warm_jobs(tmp_path):
    from sam2consensus_tpu.serve import JobSpec

    paths = [_sim(tmp_path, f"w{k}.sam", 20 + k, contig_len=4444)
             for k in range(3)]
    runner = _runner()
    results = runner.submit_jobs(
        [JobSpec(filename=p,
                 config=RunConfig(backend="jax", pileup="scatter", shards=1))
         for p in paths])
    assert all(r.ok for r in results)
    first = results[0].metrics
    assert first.get("compile/jit_cache_miss", 0) >= 1
    for res in results[1:]:
        m = res.metrics
        # THE acceptance pin: hits on job 2+, zero re-trace anywhere
        assert m.get("compile/jit_cache_hit", 0) > 0
        assert m.get("compile/jit_cache_miss", 0) == 0
        assert not any(k.startswith("compile/trace/") for k in m), m


def test_prewarmed_shapes_never_retrace(tmp_path):
    from sam2consensus_tpu.encoder.events import GenomeLayout
    from sam2consensus_tpu.ops.pileup import canonical_slab_shapes
    from sam2consensus_tpu.serve import JobSpec

    path = _sim(tmp_path, "p.sam", 31, contig_len=7777)
    h = opener(path, binary=True)
    contigs, _n, _first = read_header(h)
    h.close()
    total_len = GenomeLayout(contigs).total_len
    runner = _runner()
    shapes = canonical_slab_shapes(total_len, read_len=100,
                                   n_reads=1200)
    assert runner.prewarm(total_len, shapes) == len(shapes)
    assert runner.prewarm(total_len, shapes) == 0   # idempotent
    server = runner.registry.snapshot()["counters"]
    assert server["compile/prewarm_shapes"] == len(shapes)
    [res] = runner.submit_jobs([JobSpec(
        filename=path, config=RunConfig(backend="jax", shards=1,
                                        pileup="scatter"))])
    assert res.ok
    # job 1 (!) already runs fully warm: its registry saw no trace at
    # all, every dispatch was a cache hit
    assert res.metrics.get("compile/jit_cache_hit", 0) > 0
    assert res.metrics.get("compile/jit_cache_miss", 0) == 0
    assert not any(k.startswith("compile/trace/") for k in res.metrics)


# -- cross-job pipelining --------------------------------------------------
def test_overlap_metric_published(tmp_path):
    from sam2consensus_tpu.serve import JobSpec

    paths = [_sim(tmp_path, f"o{k}.sam", 40 + k) for k in range(3)]
    runner = _runner()
    results = runner.submit_jobs(
        [JobSpec(filename=p,
                 config=RunConfig(backend="jax", pileup="scatter", shards=1))
         for p in paths])
    assert all(r.ok for r in results)
    # job 1 was never decode-ahead (nothing to overlap); jobs 2+ carry
    # the measured cross-job intersection (>= 0 — tiny jobs can decode
    # entirely before the previous job dispatches)
    assert "serve/overlap_sec" not in results[0].metrics
    for res in results[1:]:
        assert res.metrics.get("serve/overlap_sec", None) is not None
        assert res.metrics["serve/overlap_sec"] >= 0.0
        assert res.metrics.get("serve/decode_ahead_sec", 0) > 0.0


def test_decode_ahead_off_still_identical(tmp_path):
    from sam2consensus_tpu.serve import JobSpec

    path = _sim(tmp_path, "n.sam", 50)
    cfg = RunConfig(backend="jax", pileup="scatter", shards=1)
    runner = _runner(decode_ahead=False)
    [r1] = runner.submit_jobs([JobSpec(filename=path, config=cfg)])
    cold, _ = _cold_jax(path, cfg)
    assert _rendered(r1) == cold


# -- per-job fault isolation ----------------------------------------------
def test_midqueue_fault_demotes_only_that_job(tmp_path):
    from sam2consensus_tpu.serve import JobSpec

    paths = [_sim(tmp_path, f"f{k}.sam", 60 + k) for k in range(3)]
    base = dict(backend="jax", pileup="scatter", shards=1)
    faulty = RunConfig(**base, fault_inject="pileup_dispatch:rpc:0:inf",
                       on_device_error="fallback", retries=1,
                       retry_backoff=0.01)
    cfgs = [RunConfig(**base), faulty, RunConfig(**base)]
    runner = _runner()
    results = runner.submit_jobs(
        [JobSpec(filename=p, config=c) for p, c in zip(paths, cfgs)])
    assert [r.ok for r in results] == [True, True, True]
    # the faulting job walked the ladder (counter-pinned) yet produced
    # byte-identical output
    m1 = results[1].metrics
    assert m1.get("resilience/demotions", 0) >= 1
    assert results[1].rungs.get("pileup") == "host"
    clean_cfg = RunConfig(**base)
    for k in (0, 1, 2):
        cold, _ = _cold_jax(paths[k], clean_cfg)
        assert _rendered(results[k]) == cold
    # ...and the NEXT job never saw the demotion: fast path, warm
    m2 = results[2].metrics
    assert m2.get("resilience/demotions", 0) == 0
    assert results[2].rungs == {}
    assert m2.get("compile/jit_cache_hit", 0) > 0
    assert "pileup_ladder" not in results[2].stats.extra


def test_failed_job_does_not_kill_the_server(tmp_path):
    from sam2consensus_tpu.serve import JobSpec

    good = _sim(tmp_path, "g.sam", 70)
    cfg = RunConfig(backend="jax", pileup="scatter", shards=1)
    runner = _runner()
    results = runner.submit_jobs([
        JobSpec(filename=good, config=cfg),
        JobSpec(filename=os.path.join(str(tmp_path), "missing.sam"),
                config=cfg),
        JobSpec(filename=good, config=cfg),
    ])
    assert [r.ok for r in results] == [True, False, True]
    assert "FileNotFoundError" in results[1].error
    cold, _ = _cold_jax(good, cfg)
    assert _rendered(results[2]) == cold
    assert runner.registry.value("serve/jobs_failed") == 1


def test_serve_rejects_checkpoint_jobs(tmp_path):
    from sam2consensus_tpu.serve import JobSpec

    path = _sim(tmp_path, "r.sam", 80)
    runner = _runner()
    with pytest.raises(ValueError, match="checkpoint"):
        runner.submit_jobs([JobSpec(
            filename=path,
            config=RunConfig(backend="jax",
                             checkpoint_dir=str(tmp_path)))])
    # non-composable combos the one-shot CLI rejects are rejected here
    # too (API ValueError; the serve CLI turns the same combo into a
    # clean SystemExit up front)
    with pytest.raises(ValueError, match="does not compose"):
        runner.submit_jobs([JobSpec(
            filename=path,
            config=RunConfig(backend="jax", pileup="host", shards=2))])
    from sam2consensus_tpu import cli

    with pytest.raises(SystemExit, match="does not compose"):
        cli.main(["serve", "-i", path, "--pileup", "host",
                  "--shards", "2", "--quiet"])


def test_env_metrics_out_suffixed_per_job(tmp_path, monkeypatch):
    """S2C_METRICS_OUT names ONE path; serve must not let N jobs
    overwrite each other's metrics/manifest there."""
    from sam2consensus_tpu.serve import JobSpec

    paths = [_sim(tmp_path, f"e{k}.sam", 85 + k) for k in range(2)]
    base = str(tmp_path / "envm.jsonl")
    monkeypatch.setenv("S2C_METRICS_OUT", base)
    runner = _runner()
    results = runner.submit_jobs(
        [JobSpec(filename=p,
                 config=RunConfig(backend="jax", pileup="scatter",
                                  shards=1))
         for p in paths])
    assert all(r.ok for r in results)
    assert os.path.exists(base + ".job0")
    assert os.path.exists(base + ".job1")
    assert not os.path.exists(base)


# -- the CLI entry ---------------------------------------------------------
def test_serve_cli_end_to_end(tmp_path):
    from sam2consensus_tpu import cli

    a = _sim(tmp_path, "cli_a.sam", 90)
    b = _sim(tmp_path, "cli_b.sam.gz", 91, gz=True)
    out = tmp_path / "out"
    mbase = str(tmp_path / "metrics")
    rc = cli.main(["serve", "-i", a, "-i", b, "-o", str(out),
                   "--pileup", "scatter", "--quiet",
                   "--metrics-out", mbase])
    assert rc == 0
    cold_out = tmp_path / "cold"
    for path in (a, b):
        assert cli.main(["-i", path, "-o", str(cold_out),
                         "--backend", "jax", "--pileup", "scatter",
                         "--quiet"]) == 0
    warm_files = sorted(os.listdir(out))
    assert warm_files == sorted(os.listdir(cold_out))
    for f in warm_files:
        assert (out / f).read_text() == (cold_out / f).read_text(), f
    # per-job metrics + manifests were written
    for k in (0, 1):
        assert os.path.exists(f"{mbase}.job{k}.jsonl")
        man = json.load(open(f"{mbase}.job{k}.jsonl.manifest.json"))
        assert man["schema"] == "s2c-manifest/1"
        if k > 0:
            assert "serve/overlap_sec" in man["serve"]


def test_serve_cli_rejects_bad_fault_spec():
    from sam2consensus_tpu import cli

    with pytest.raises(SystemExit):
        cli.main(["serve", "-i", "x.sam", "--fault-inject",
                  "nonsense//"])


# -- persistent compilation cache (satellite) ------------------------------
def test_persistent_cache_cross_process(tmp_path):
    """Cold process 2 hits the on-disk cache process 1 populated, and
    both record compile/persist_{hit,miss} via the monitoring hook."""
    cache = str(tmp_path / "jitcache")
    code = (
        "import sys; sys.path.insert(0, {repo!r})\n"
        "from sam2consensus_tpu.observability.jitcache import "
        "setup_persistent_cache\n"
        "from sam2consensus_tpu.observability.metrics import current\n"
        "assert setup_persistent_cache() == {cache!r}\n"
        "from sam2consensus_tpu.ops.pileup import prewarm_scatter\n"
        "prewarm_scatter(901, [(64, 32)])\n"
        "c = current().snapshot()['counters']\n"
        "import json; print(json.dumps({{k: v for k, v in c.items()"
        " if k.startswith('compile/persist')}}))\n"
    ).format(repo=REPO, cache=cache)
    env = dict(os.environ, S2C_JIT_CACHE=cache, JAX_PLATFORMS="cpu")
    outs = []
    for _ in range(2):
        r = subprocess.run([sys.executable, "-c", code], env=env,
                           capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stderr[-2000:]
        outs.append(json.loads(r.stdout.strip().splitlines()[-1]))
    assert outs[0].get("compile/persist_miss", 0) > 0
    assert outs[1].get("compile/persist_hit", 0) > 0
    assert outs[1].get("compile/persist_miss", 0) == 0
    assert os.listdir(cache)            # entries actually on disk


def test_jit_cache_env_empty_disables(monkeypatch):
    from sam2consensus_tpu.observability import jitcache

    monkeypatch.setenv("S2C_JIT_CACHE", "")
    assert jitcache.cache_dir() is None
    assert jitcache.setup_persistent_cache() is None
    monkeypatch.delenv("S2C_JIT_CACHE")
    assert jitcache.cache_dir() == jitcache.DEFAULT_CACHE_DIR
