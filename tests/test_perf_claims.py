"""Tier-1 evidence lint: perf claims in the docs must cite artifacts.

Runs ``tools/check_perf_claims.py`` against the repo's PERF.md and
README.md: any ``N Mcells/s`` / ``N×`` claim paragraph must cite a
committed measurement artifact (``campaign/``, ``perf/``,
``BENCH_rNN.json``...) that exists, or carry an explicit
``model-only`` / ``no-artifact:`` marker.  This is the structural fix
for VERDICT r5 #2/#3 ("the number is quoted with no artifact") — a PR
cannot land an uncited claim without failing tier-1.
"""

import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import check_perf_claims  # noqa: E402


def test_docs_cite_artifacts(capsys):
    rc = check_perf_claims.main(["--repo", REPO])
    out = capsys.readouterr()
    assert rc == 0, f"uncited perf claims:\n{out.out}"


def test_lint_catches_uncited_claim(tmp_path):
    (tmp_path / "PERF.md").write_text(
        "The kernel now runs 500 Mcells/s, a 9.2× win.\n")
    assert check_perf_claims.main(["--repo", str(tmp_path)]) == 1


def test_lint_accepts_cited_and_exempt_claims(tmp_path):
    os.makedirs(tmp_path / "campaign")
    (tmp_path / "campaign" / "x.jsonl").write_text("{}\n")
    (tmp_path / "PERF.md").write_text(
        "The kernel runs 500 Mcells/s (campaign/x.jsonl).\n\n"
        "On a fast link this would flip 3× (model-only until the "
        "campaign leg lands).\n")
    assert check_perf_claims.main(["--repo", str(tmp_path)]) == 0


def test_lint_catches_missing_cited_artifact(tmp_path):
    (tmp_path / "README.md").write_text(
        "A 9.2× win (campaign/never_committed.jsonl).\n")
    assert check_perf_claims.main(["--repo", str(tmp_path)]) == 1


def test_code_blocks_are_skipped(tmp_path):
    (tmp_path / "PERF.md").write_text(
        "```\n$ bench says 500 Mcells/s and 9.2×\n```\n")
    assert check_perf_claims.main(["--repo", str(tmp_path)]) == 0


# -- multi-host bench artifact lint (ISSUE 18) ----------------------------
def _bench_rows(identical=True, multihost=True, residual=True,
                mesh_admit=True, with_summary=True):
    import json

    row1 = {"kind": "row", "config": "p1d8", "hosts": 1, "shards": 8,
            "admission": "reject:capacity"}
    row2 = {"kind": "row", "config": "p2d4",
            "hosts": 2 if multihost else 1,
            "shards": 4 if multihost else 1,
            "admission": ("admit:mesh_2" if mesh_admit
                          else "admit")}
    for r in (row1, row2):
        r["identical_fasta"] = bool(identical)
        if residual:
            r["capacity_residual"] = 2.0
            r["capacity_in_band"] = True
    rows = [row1, row2]
    if with_summary:
        rows.append({"kind": "summary", "ok": True, "failures": 0,
                     "identical_all": bool(identical),
                     "capacity_in_band_all": True})
    return "\n".join(json.dumps(r) for r in rows) + "\n"


def test_committed_multihost_bench_artifact_is_valid_evidence():
    path = os.path.join(REPO, "campaign",
                        "multihost_bench_r06_cpufallback.jsonl")
    assert os.path.exists(path)
    assert check_perf_claims.lint_multihost_bench_artifact(path) == []


@pytest.mark.parametrize("kw,needle", [
    (dict(), None),                                  # well-formed -> clean
    (dict(identical=False), "identical_fasta is false"),
    (dict(multihost=False), "no row ran multi-host"),
    (dict(residual=False), "no capacity residual"),
    (dict(mesh_admit=False), "mesh_shards admission verdict"),
    (dict(with_summary=False), "no summary row"),
])
def test_multihost_bench_lint_structure(tmp_path, kw, needle):
    path = tmp_path / "multihost_bench_r99.jsonl"
    path.write_text(_bench_rows(**kw))
    errs = check_perf_claims.lint_multihost_bench_artifact(str(path))
    if needle is None:
        assert errs == []
    else:
        assert any(needle in e for e in errs), errs


def test_cited_multihost_bench_artifact_must_lint(tmp_path):
    # a PERF.md claim citing a structurally-broken bench JSONL fails
    os.makedirs(tmp_path / "campaign")
    (tmp_path / "campaign" / "multihost_bench_r99.jsonl").write_text(
        _bench_rows(identical=False))
    (tmp_path / "PERF.md").write_text(
        "Sharding wins 2× (campaign/multihost_bench_r99.jsonl).\n")
    assert check_perf_claims.main(["--repo", str(tmp_path)]) == 1
    (tmp_path / "campaign" / "multihost_bench_r99.jsonl").write_text(
        _bench_rows())
    assert check_perf_claims.main(["--repo", str(tmp_path)]) == 0


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
