"""Tier-1 evidence lint: perf claims in the docs must cite artifacts.

Runs ``tools/check_perf_claims.py`` against the repo's PERF.md and
README.md: any ``N Mcells/s`` / ``N×`` claim paragraph must cite a
committed measurement artifact (``campaign/``, ``perf/``,
``BENCH_rNN.json``...) that exists, or carry an explicit
``model-only`` / ``no-artifact:`` marker.  This is the structural fix
for VERDICT r5 #2/#3 ("the number is quoted with no artifact") — a PR
cannot land an uncited claim without failing tier-1.
"""

import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import check_perf_claims  # noqa: E402


def test_docs_cite_artifacts(capsys):
    rc = check_perf_claims.main(["--repo", REPO])
    out = capsys.readouterr()
    assert rc == 0, f"uncited perf claims:\n{out.out}"


def test_lint_catches_uncited_claim(tmp_path):
    (tmp_path / "PERF.md").write_text(
        "The kernel now runs 500 Mcells/s, a 9.2× win.\n")
    assert check_perf_claims.main(["--repo", str(tmp_path)]) == 1


def test_lint_accepts_cited_and_exempt_claims(tmp_path):
    os.makedirs(tmp_path / "campaign")
    (tmp_path / "campaign" / "x.jsonl").write_text("{}\n")
    (tmp_path / "PERF.md").write_text(
        "The kernel runs 500 Mcells/s (campaign/x.jsonl).\n\n"
        "On a fast link this would flip 3× (model-only until the "
        "campaign leg lands).\n")
    assert check_perf_claims.main(["--repo", str(tmp_path)]) == 0


def test_lint_catches_missing_cited_artifact(tmp_path):
    (tmp_path / "README.md").write_text(
        "A 9.2× win (campaign/never_committed.jsonl).\n")
    assert check_perf_claims.main(["--repo", str(tmp_path)]) == 1


def test_code_blocks_are_skipped(tmp_path):
    (tmp_path / "PERF.md").write_text(
        "```\n$ bench says 500 Mcells/s and 9.2×\n```\n")
    assert check_perf_claims.main(["--repo", str(tmp_path)]) == 0


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
