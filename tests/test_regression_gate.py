"""The noise-aware perf regression gate (observability/regress.py +
tools/regress_check.py) — tier-1 wiring.

Pins the ISSUE's acceptance list: the gate exits 0 on the committed
``BENCH_r01..r05`` trajectory (including the head-truncated tail
captures and the crashed r01 round), exits nonzero on a synthetic
regressed row, honors min-repeat awareness, and judges deltas with
median/MAD bands instead of naive round-over-round comparison.
"""

import importlib.util
import json
import os
import sys

import pytest

from sam2consensus_tpu.observability import regress

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool():
    spec = importlib.util.spec_from_file_location(
        "regress_check", os.path.join(REPO, "tools", "regress_check.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


regress_check = _load_tool()


# -- band/verdict units ----------------------------------------------------
def test_check_series_directions():
    hist = [100.0, 102.0, 98.0, 101.0]
    # throughput-like (higher better): a crash regresses, a jump improves
    assert regress.check_series(hist, 40.0)["status"] == "regressed"
    assert regress.check_series(hist, 180.0)["status"] == "improved"
    assert regress.check_series(hist, 95.0)["status"] == "pass"
    # seconds-like (lower better): the directions flip
    assert regress.check_series(hist, 250.0,
                                lower_is_better=True)["status"] \
        == "regressed"
    assert regress.check_series(hist, 40.0,
                                lower_is_better=True)["status"] \
        == "improved"


def test_check_series_min_repeats():
    v = regress.check_series([100.0, 101.0], 10.0)
    assert v["status"] == "insufficient_history"
    assert v["n_history"] == 2
    # with the repeats present the same candidate regresses
    assert regress.check_series([100.0, 101.0, 99.0],
                                10.0)["status"] == "regressed"


def test_noise_floor_rel_floor_guards_quiet_history():
    # three identical points: MAD = 0, but ordinary rig noise must not
    # flag — the relative floor carries the band
    hist = [10.0, 10.0, 10.0]
    assert regress.check_series(hist, 12.0)["status"] == "pass"
    assert regress.check_series(hist, 2.0)["status"] == "regressed"


def test_mad_band_tolerates_one_wild_round():
    # one 2x outlier round in the history must not explode the center
    hist = [10.0, 10.5, 9.8, 21.0, 10.2]
    v = regress.check_series(hist, 10.0)
    assert v["status"] == "pass"
    assert v["median"] == pytest.approx(10.2)


# -- artifact tolerance ----------------------------------------------------
def test_extract_rows_from_truncated_capture():
    # a head-truncated capture: the first row is cut mid-object, the
    # rest are intact — exactly the committed BENCH_r0* shape
    text = ('es_per_s": 42.0}, "identical": true}, '
            '{"config": "a", "jax_sec": 1.5, "vs_baseline": 10.0}, '
            '{"config": "b", "jax_sec": 0.5, "vs_baseline": 20.0}]}')
    rows = regress.extract_bench_rows(text)
    assert [r["config"] for r in rows] == ["a", "b"]
    assert rows[0]["vs_baseline"] == 10.0


def test_committed_trajectory_loads():
    paths = sorted(os.path.join(REPO, f"BENCH_r0{i}.json")
                   for i in range(1, 6))
    per_round = [regress.load_bench_artifact(p) for p in paths]
    # r01 crashed (rc=1): no recoverable rows; later rounds have rows
    assert per_round[0] == []
    assert all(len(rows) > 0 for rows in per_round[1:])
    series = regress.bench_series(paths)
    assert ("north_star", "vs_baseline") in series


# -- the CI gate -----------------------------------------------------------
def test_gate_passes_on_committed_history(capsys):
    """THE acceptance pin: the gate must exit 0 on the repo's own
    committed bench trajectory."""
    rc = regress_check.main([])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "0 regression(s)" in out


def _write_round(tmp_path, i, vs_baseline, jax_sec):
    # the driver-wrapper shape the real trajectory uses
    inner = json.dumps({"configs": [
        {"config": "north_star", "vs_baseline": vs_baseline,
         "jax_sec": jax_sec, "identical": True}]})
    path = tmp_path / f"BENCH_t{i:02d}.json"
    path.write_text(json.dumps({"rc": 0, "tail": inner + "\n",
                                "parsed": None}))
    return str(path)


def test_gate_fails_on_synthetic_regression(tmp_path, capsys):
    paths = [_write_round(tmp_path, i, vs, sec)
             for i, (vs, sec) in enumerate(
                 [(100.0, 1.0), (104.0, 0.97), (98.0, 1.03),
                  (101.0, 1.0)])]
    paths.append(_write_round(tmp_path, 9, 30.0, 3.4))   # the crash
    rc = regress_check.main(paths)
    out = capsys.readouterr().out
    assert rc == 1, out
    assert "REGRESSED: north_star/vs_baseline" in out
    assert "REGRESSED: north_star/jax_sec" in out


def test_gate_min_repeats_passes_short_history(tmp_path, capsys):
    paths = [_write_round(tmp_path, 0, 100.0, 1.0),
             _write_round(tmp_path, 1, 101.0, 1.0),
             _write_round(tmp_path, 9, 30.0, 3.4)]
    rc = regress_check.main(paths)
    out = capsys.readouterr().out
    assert rc == 0, out                # 2 priors < min_repeats: loud pass
    assert "pass (2 repeats)" in out


def test_gate_improvement_is_not_a_failure(tmp_path):
    paths = [_write_round(tmp_path, i, 100.0 + i, 1.0)
             for i in range(4)]
    paths.append(_write_round(tmp_path, 9, 400.0, 0.25))
    assert regress_check.main(paths) == 0


def test_gate_json_output(tmp_path, capsys):
    paths = [_write_round(tmp_path, i, v, 1.0)
             for i, v in enumerate([100.0, 99.0, 101.0, 100.0])]
    rc = regress_check.main(paths + ["--json"])
    blob = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert blob["regressed"] == 0
    assert any(v["config"] == "north_star" for v in blob["verdicts"])


# -- full-row sibling artifacts (round 6+) ---------------------------------
def test_full_sibling_preferred_over_truncated_capture(tmp_path):
    """bench.py now writes BENCH_<tag>.full.json; the loader must read
    it INSTEAD of scanning the truncated capture."""
    cap = tmp_path / "BENCH_t10.json"
    # the capture itself is hopelessly truncated mid-object
    cap.write_text('es_per_s": 42.0}, {"config": "stale", "jax_')
    full = tmp_path / "BENCH_t10.full.json"
    full.write_text(json.dumps({"configs": [
        {"config": "north_star", "vs_baseline": 19.0, "jax_sec": 1.1},
        {"config": "serve_warm", "vs_baseline": 6.1, "jax_sec": 0.55},
    ]}))
    rows = regress.load_bench_artifact(str(cap))
    assert [r["config"] for r in rows] == ["north_star", "serve_warm"]
    # a corrupt sibling falls back to the capture scan
    full.write_text("not json at all")
    assert regress.load_bench_artifact(str(cap)) == []


def test_full_sibling_path_mapping():
    assert regress.full_sibling_path("BENCH_r06.json") \
        == "BENCH_r06.full.json"
    assert regress.full_sibling_path("BENCH_r06.full.json") \
        == "BENCH_r06.full.json"


def test_discover_default_excludes_full_siblings(tmp_path):
    (tmp_path / "BENCH_r06.json").write_text("{}")
    (tmp_path / "BENCH_r06.full.json").write_text("{}")
    paths = regress_check.discover_default(str(tmp_path))
    assert [os.path.basename(p) for p in paths] == ["BENCH_r06.json"]


# -- the serve_warm series (round 6+) --------------------------------------
def _write_serve_round(tmp_path, i, vs_cold, warm_sec):
    inner = json.dumps({"configs": [
        {"config": "serve_warm", "vs_baseline": vs_cold,
         "vs_baseline_kind": "cold_process", "jax_sec": warm_sec,
         "identical": True}]})
    path = tmp_path / f"BENCH_s{i:02d}.json"
    path.write_text(json.dumps({"rc": 0, "tail": inner + "\n"}))
    return str(path)


def test_gate_judges_serve_series(tmp_path, capsys):
    """Once >=1 round of serve history exists, the warm-path numbers
    regress like any other series: a warm-per-job blowup (or a cold/warm
    ratio collapse) fails the gate."""
    paths = [_write_serve_round(tmp_path, i, vs, sec)
             for i, (vs, sec) in enumerate(
                 [(6.0, 0.55), (5.7, 0.58), (6.3, 0.52), (6.0, 0.56)])]
    assert regress_check.main(list(paths)) == 0
    paths.append(_write_serve_round(tmp_path, 9, 1.1, 3.2))
    rc = regress_check.main(paths)
    out = capsys.readouterr().out
    assert rc == 1, out
    assert "REGRESSED: serve_warm/vs_baseline" in out
    assert "REGRESSED: serve_warm/jax_sec" in out


# -- chaos soak: recovery_sec rides the gate (r6) --------------------------
def test_committed_chaos_soak_artifact_parses_and_gates(capsys):
    """The committed chaos-soak artifact is well-formed (every cycle
    byte-identical, zero lost/duplicated) and its recovery_sec series
    runs through the JSONL gate mode without erroring — the per-mode
    groups are the series future rounds regress against."""
    path = os.path.join(REPO, "campaign",
                        "chaos_soak_r06_cpufallback.jsonl")
    rows = [json.loads(l) for l in open(path) if l.strip()]
    summary = [r for r in rows if r.get("mode") == "summary"][0]
    cycles = [r for r in rows if "cycle" in r]
    assert summary["cycles"] >= 8 and len(cycles) >= 8
    assert summary["identical_all"] is True
    assert summary["lost_total"] == 0
    assert summary["duplicated_total"] == 0
    assert summary["killed_cycles"] >= 2     # SIGKILLs actually landed
    assert {"kill", "hang", "fault", "kill_fault"} <= {
        r["mode"] for r in cycles}
    assert all(r["recovery_sec"] <= summary["max_recovery_bound_sec"]
               for r in cycles)
    # the gate ingests it (one committed round = insufficient history
    # per mode -> loud pass, never a crash)
    rc = regress_check.main(["--jsonl", path, "--group-by", "mode",
                             "--value", "recovery_sec",
                             "--lower-is-better"])
    capsys.readouterr()
    assert rc == 0


def test_gate_fails_on_synthetic_recovery_regression(tmp_path, capsys):
    path = tmp_path / "soak.jsonl"
    rows = [{"mode": "kill", "recovery_sec": s}
            for s in (9.0, 9.5, 8.8, 9.2, 60.0)]   # regressed tail
    path.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    rc = regress_check.main(["--jsonl", str(path), "--group-by", "mode",
                             "--value", "recovery_sec",
                             "--lower-is-better"])
    out = capsys.readouterr().out
    assert rc == 1, out
    assert "REGRESSED: kill/recovery_sec" in out


# -- fleet soak: drain_sec rides the gate (ISSUE 15) -----------------------
def test_committed_fleet_soak_artifact_parses_and_gates(capsys):
    """The committed fleet-soak artifact is well-formed (cycle
    invariants are pinned in tests/test_fleet.py) and its drain_sec
    series runs through the JSONL gate mode without erroring — the
    per-mode groups (kill/wedge/fault + serial_drain/fleet_drain) are
    the series future rounds regress against."""
    path = os.path.join(REPO, "campaign",
                        "fleet_soak_r06_cpufallback.jsonl")
    rows = [json.loads(l) for l in open(path) if l.strip()]
    modes = {r["mode"] for r in rows if "drain_sec" in r}
    assert {"kill", "wedge", "fault", "serial_drain",
            "fleet_drain"} <= modes
    rc = regress_check.main(["--jsonl", path, "--group-by", "mode",
                             "--value", "drain_sec",
                             "--lower-is-better"])
    capsys.readouterr()
    assert rc == 0


def test_gate_fails_on_synthetic_drain_regression(tmp_path, capsys):
    path = tmp_path / "fleet.jsonl"
    rows = [{"mode": "fleet_drain", "drain_sec": s}
            for s in (4.0, 4.2, 3.9, 4.1, 30.0)]   # regressed tail
    path.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    rc = regress_check.main(["--jsonl", str(path), "--group-by",
                             "mode", "--value", "drain_sec",
                             "--lower-is-better"])
    out = capsys.readouterr().out
    assert rc == 1, out
    assert "REGRESSED: fleet_drain/drain_sec" in out


# -- campaign JSONL mode ---------------------------------------------------
def test_gate_jsonl_series(tmp_path, capsys):
    path = tmp_path / "sweep.jsonl"
    rows = [{"point": "w128", "median_sec": s}
            for s in (1.0, 1.02, 0.98, 1.01, 4.0)]   # regressed tail
    rows += [{"point": "w256", "median_sec": s}
             for s in (2.0, 2.05, 1.95, 2.0, 2.02)]  # stable
    rows.append({"malformed": True})
    path.write_text("\n".join(json.dumps(r) for r in rows) + "\nnot json\n")
    rc = regress_check.main(["--jsonl", str(path), "--group-by", "point",
                             "--value", "median_sec",
                             "--lower-is-better"])
    out = capsys.readouterr().out
    assert rc == 1, out
    assert "REGRESSED: w128/median_sec" in out
