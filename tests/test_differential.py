"""Golden-oracle differential tests: jax backend ⇔ cpu backend, byte for byte.

This is the operational meaning of BASELINE.md's correctness gate ("FASTA
byte-identity vs CPU backend", SURVEY.md §4).  Every corpus entry renders the
full output files (headers + wrapping) for both backends and compares text.
"""

import io

import pytest

from sam2consensus_tpu.backends.cpu import CpuBackend
from sam2consensus_tpu.backends.jax_backend import JaxBackend
from sam2consensus_tpu.config import RunConfig
from sam2consensus_tpu.io.fasta import render_file
from sam2consensus_tpu.io.sam import iter_records, read_header
from sam2consensus_tpu.utils.simulate import (BASELINE_SPECS, SimSpec,
                                              sam_text, simulate)


def rendered(backend, text, cfg):
    handle = io.StringIO(text)
    contigs, _n, first = read_header(handle)
    res = backend.run(contigs, iter_records(handle, first), cfg)
    return {name: render_file(recs, cfg.nchar)
            for name, recs in res.fastas.items()}


def assert_identical(text, **cfg_kwargs):
    cfg = RunConfig(prefix="p", **cfg_kwargs)
    out_cpu = rendered(CpuBackend(), text, cfg)
    out_jax = rendered(JaxBackend(), text, cfg)
    assert out_jax == out_cpu


HANDCRAFTED = {
    "basic": sam_text([("ref1", 10)], [
        ("ref1", 1, "4M", "ACGT"), ("ref1", 3, "2M", "GT")]),
    "ties": sam_text([("r", 1)], [
        ("r", 1, "1M", "A"), ("r", 1, "1M", "A"),
        ("r", 1, "1M", "C"), ("r", 1, "1M", "C"), ("r", 1, "1M", "T")]),
    "deletion": sam_text([("r", 8)], [("r", 1, "2M3D2M", "ACGT")]),
    "insertions": sam_text([("r", 6)], [
        ("r", 1, "3M", "AAA"), ("r", 1, "3M", "AAA"), ("r", 1, "3M", "AAA"),
        ("r", 1, "2M2I1M", "AACCA")]),
    "ins_no_cov": sam_text([("r", 2)], [("r", 1, "1M2I", "ACC")]),
    "ins_at_end": sam_text([("r", 2)], [("r", 1, "2M2I", "AACC")]),
    "neg_pos_wrap": sam_text([("r", 4)], [
        ("r", 0, "2M", "AC"), ("r", 1, "1M", "G")]),
    "multi_contig": sam_text([("a", 5), ("b", 7), ("zero", 3)], [
        ("a", 1, "5M", "ACGTA"), ("b", 3, "4M", "TTTT"),
        ("b", 1, "2M1I3M", "GGCAAA")]),
    "n_bases": sam_text([("r", 3)], [
        ("r", 1, "3M", "ANA"), ("r", 1, "3M", "NNA"), ("r", 1, "3M", "AGA")]),
    "all_ops": sam_text([("r", 20)], [
        ("r", 3, "2S3M1I2M2D1M2H", "TTACGTCAGX"[:9]),
        ("r", 1, "5M", "ACGTA"), ("r", 10, "3=1X2M", "ACGTAC")]),
}


@pytest.mark.parametrize("name", sorted(HANDCRAFTED))
def test_handcrafted_identical(name):
    assert_identical(HANDCRAFTED[name])


@pytest.mark.parametrize("name", sorted(HANDCRAFTED))
def test_handcrafted_identical_multithreshold(name):
    assert_identical(HANDCRAFTED[name], thresholds=[0.25, 0.5, 0.75, 1.0])


def test_simulated_phix_like():
    spec = BASELINE_SPECS["phix_like"]
    spec = SimSpec(**{**spec.__dict__, "n_reads": 800, "contig_len": 800})
    assert_identical(simulate(spec), thresholds=[0.25, 0.5, 0.75])


def test_simulated_target_capture():
    spec = BASELINE_SPECS["target_capture"]
    spec = SimSpec(**{**spec.__dict__, "n_contigs": 25, "n_reads": 1500,
                      "contig_len": 300})
    assert_identical(simulate(spec), thresholds=[0.25, 0.75])


def test_simulated_amplicon_deep():
    spec = BASELINE_SPECS["amplicon_deep"]
    spec = SimSpec(**{**spec.__dict__, "n_reads": 3000, "contig_len": 200})
    assert_identical(simulate(spec), thresholds=[0.25, 0.5], min_depth=10)


def test_min_depth_and_fill_variants():
    text = simulate(SimSpec(n_contigs=3, contig_len=150, n_reads=120,
                            read_len=40, seed=9))
    assert_identical(text, min_depth=3, fill="N")
    assert_identical(text, min_depth=2, fill="?")


def test_maxdel_variants():
    text = simulate(SimSpec(n_contigs=2, contig_len=200, n_reads=300,
                            read_len=50, del_read_rate=0.5, max_indel=5,
                            seed=11))
    assert_identical(text, maxdel=2)
    assert_identical(text, maxdel=None)
    assert_identical(text, maxdel=0)


def test_wrapping_identical():
    text = HANDCRAFTED["multi_contig"]
    cfg = RunConfig(prefix="p", nchar=3)
    assert rendered(JaxBackend(), text, cfg) == rendered(CpuBackend(), text, cfg)


def test_odd_thresholds_float_fidelity():
    # thresholds with inexact float64 representations exercise the integer
    # cutoff LUT (ops/vote.py threshold_luts) against the oracle's raw
    # float comparison
    text = simulate(SimSpec(n_contigs=2, contig_len=120, n_reads=600,
                            read_len=30, seed=13))
    assert_identical(text, thresholds=[0.1, 0.3, 0.33, 0.66, 0.9, 1.0])


def test_permissive_mode_identical():
    text = sam_text([("r", 4)], [
        ("other", 1, "2M", "AC"),      # unknown ref -> skipped
        ("r", 3, "4M", "ACGT"),        # overruns contig -> skipped
        ("r", 1, "2M", "ac"),          # bad alphabet -> skipped
        ("r", 1, "3M", "ACG"),
    ])
    assert_identical(text, strict=False)


def test_literal_dash_in_seq_counts_toward_maxdel():
    # '-' is in the count alphabet: literal dashes in SEQ vote for gaps and
    # count toward the maxdel gate (seqout.count('-') gates them all).
    text = sam_text([("r", 4)], [
        ("r", 1, "4M", "A--T"),
        ("r", 1, "4M", "ACGT"),
    ])
    assert_identical(text, maxdel=1)
    assert_identical(text, maxdel=2)
    assert_identical(text, thresholds=[0.25, 0.75], maxdel=1)


def test_invalid_motif_base_both_backends_raise():
    """Strict errors match the oracle in TYPE and MESSAGE — the jax
    backend's tracebacks are the reference's tracebacks."""
    text = sam_text([("r", 6)], [("r", 1, "2M2I2M", "AAxxGG")])
    cfg = RunConfig(prefix="p")
    with pytest.raises(KeyError) as e_cpu:
        rendered(CpuBackend(), text, cfg)
    with pytest.raises(KeyError) as e_jax:
        rendered(JaxBackend(), text, cfg)
    assert str(e_cpu.value) == str(e_jax.value)
    # permissive mode: both skip the read entirely, identical output
    assert_identical(text, strict=False)


def test_short_seq_concatenation_semantics_identical():
    """SEQ shorter than its CIGAR claims (out-of-contract): the reference
    builds seqout by CONCATENATION, shifting later ops left — a '10M' with
    a 2-base SEQ spans 2 positions, not 10, and is ACCEPTED on a 6-long
    contig; a '4M2D' with 2 bases puts the gap at positions 2-3, not 4-5.
    Both backends must agree byte-for-byte (and with the native decoder,
    which replays such lines through the python encoder)."""
    text = sam_text([("r", 6)], [
        ("r", 1, "10M", "AC"),        # claimed span 10 > contig; emitted 2
        ("r", 1, "4M2D", "GG"),       # gap shifts left to output cols 2-3
        ("r", 1, "6M", "TTTTTT"),     # in-contract anchor
    ])
    assert_identical(text, thresholds=[0.25, 0.75])
    assert_identical(text, strict=False)


@pytest.mark.parametrize("record,exc", [
    (("other", 1, "2M", "AC"), KeyError),      # unknown reference
    (("r", 5, "3M", "ACG"), IndexError),       # overruns the contig
    (("r", 1, "2M", "ac"), KeyError),          # out-of-alphabet SEQ
])
def test_strict_error_parity_types_and_messages(record, exc):
    text = sam_text([("r", 6)], [record])
    cfg = RunConfig(prefix="p")
    with pytest.raises(exc) as e_cpu:
        rendered(CpuBackend(), text, cfg)
    with pytest.raises(exc) as e_jax:
        rendered(JaxBackend(), text, cfg)
    assert str(e_cpu.value) == str(e_jax.value)
    assert_identical(text, strict=False)       # permissive: both skip


def test_zero_span_read_beyond_contig_accepted():
    # all-S/H/I CIGARs touch no position; the reference runs a zero-iteration
    # loop and accepts them at any POS.
    text = sam_text([("r", 4)], [
        ("r", 9, "2S", "TT"),
        ("r", 9, "3H", "*"),
        ("r", 1, "4M", "ACGT"),
    ])
    assert_identical(text)


def test_short_seq_insertion_key_uses_claimed_cursor():
    """The reference's MIXED out-of-contract semantics: seqout is built by
    concatenation (bases/gaps shift left on short M ops) but insertion
    keys advance by CLAIMED lengths — a '6M2I2M' read with a 5-base SEQ
    keys its insertion at 6, past the 5 emitted cells.  Encoder must match
    the golden walker exactly."""
    from sam2consensus_tpu.core.cigar import walk
    from sam2consensus_tpu.encoder.events import GenomeLayout, ReadEncoder
    from sam2consensus_tpu.io.sam import Contig, SamRecord

    seqout, insert = walk("6M2I2M", "ACGGT", 0)
    layout = GenomeLayout([Contig("r", 20)])
    enc = ReadEncoder(layout)
    enc.encode_record(SamRecord("r", 0, "6M2I2M", "ACGGT"))
    assert insert == [(6, "")], insert
    assert enc.insertions.local_pos == [6]
    # and both backends agree byte-for-byte on such input
    text = sam_text([("r", 20)], [("r", 1, "6M2I2M", "ACGGT"),
                                  ("r", 1, "20M", "A" * 20)])
    assert_identical(text)


def test_trailing_empty_contig_contig_sums():
    """A zero-length contig at the END of the layout must not shift or
    truncate its neighbors' per-contig coverage sums (round-4 review:
    the segmented-reduction rewrite clamped the empty contig's start
    into the last real position and dropped cov[L-1] from the final
    non-empty contig)."""
    text = sam_text([("a", 3), ("mid0", 0), ("b", 4), ("z", 0)], [
        ("a", 1, "3M", "ACG"),
        ("b", 1, "4M", "TTTT"),
        ("b", 4, "1M", "T"),       # covers b's last position, cov[L-1]
    ])
    assert_identical(text)
