"""The observability subsystem: spans, metrics, exports, compat view.

Pins the tentpole contracts (the ISSUE's acceptance list):

* span nesting/ordering and thread isolation (each thread's spans carry
  its own tid while landing in one shared list);
* disabled-mode no-op: the tracer adds < 2% to a tight loop when off;
* exported Chrome trace JSON is valid trace-event format (``ph``,
  ``ts``, ``dur``, ``pid``/``tid`` on every complete event);
* a full jax-backend run under ``--trace-out`` produces the pipeline
  span tree and a metrics JSONL whose phase counters agree with the
  legacy ``stats.extra`` compat view bench.py reads.
"""

import io
import json
import threading
import time

import pytest

from sam2consensus_tpu import observability as obs
from sam2consensus_tpu.observability.export import (chrome_trace_events,
                                                    read_metrics_jsonl)
from sam2consensus_tpu.observability.metrics import MetricsRegistry
from sam2consensus_tpu.observability.trace import Tracer


# -- tracer core -----------------------------------------------------------
def test_span_nesting_and_ordering():
    tr = Tracer(enabled=True)
    with tr.span("outer", kind="phase"):
        time.sleep(0.002)
        with tr.span("inner"):
            time.sleep(0.001)
    spans = {s.name: s for s in tr.drain()}
    outer, inner = spans["outer"], spans["inner"]
    # inner closed first (recorded first), nested strictly inside outer
    assert [s.name for s in tr.drain()] == ["inner", "outer"]
    assert outer.ts_us <= inner.ts_us
    assert inner.ts_us + inner.dur_us <= outer.ts_us + outer.dur_us + 1.0
    assert outer.args == {"kind": "phase"}


def test_span_events_and_args():
    tr = Tracer(enabled=True)
    with tr.span("phase") as sp:
        sp.event("decision", chosen="cpu", cpu_sec=0.1)
        sp.set_args(rows=7)
    (s,) = tr.drain()
    assert s.args == {"rows": 7}
    (name, ts, args) = s.events[0]
    assert name == "decision" and args["chosen"] == "cpu"
    assert s.ts_us <= ts <= s.ts_us + s.dur_us


def test_span_sync_runs_inside_span():
    tr = Tracer(enabled=True)
    ran = []
    with tr.span("device", sync=lambda: (time.sleep(0.003),
                                         ran.append(True))):
        pass
    (s,) = tr.drain()
    assert ran == [True]
    assert s.dur_us >= 2000  # the sync's sleep is inside the duration


def test_tracer_thread_safety():
    tr = Tracer(enabled=True)
    # the barrier holds every worker alive until all have started, so
    # thread idents cannot be reused (a finished thread's ident may be
    # recycled by the OS) and the 4-distinct-tids assertion is sound
    gate = threading.Barrier(4)

    def work(i):
        gate.wait()
        for k in range(50):
            with tr.span(f"t{i}", k=k):
                pass

    threads = [threading.Thread(target=work, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    spans = tr.drain()
    assert len(spans) == 200
    # each thread's spans carry its own tid; 4 distinct tids
    assert len({s.tid for s in spans}) == 4
    for name in ("t0", "t1", "t2", "t3"):
        assert sum(1 for s in spans if s.name == name) == 50


def test_disabled_tracer_is_noop_and_cheap():
    tr = Tracer(enabled=False)
    with tr.span("x") as sp:
        sp.event("e", a=1)
        sp.set_args(b=2)
    tr.event("top")
    assert tr.drain() == []

    # The < 2% budget, asserted per call: a wall-clock A/B of two loops
    # cannot resolve 2% on a shared CI host (measured noise floor here
    # is ~±10% even on 250 us bodies), so pin the absolute no-op cost
    # instead.  The real hot paths call span() once per BATCH/SLAB —
    # units of >= 100 us of work (one device dispatch ~ms, one decode
    # batch ~10 ms) — so < 2 us per disabled call IS < 2% overhead on
    # the tightest loop that actually exists, with a big margin held
    # back for slower hosts (measured ~0.5 us/call).
    n = 50_000

    def loop_span():
        t0 = time.perf_counter()
        for _ in range(n):
            with tr.span("hot"):
                pass
        return time.perf_counter() - t0

    def loop_empty():
        t0 = time.perf_counter()
        for _ in range(n):
            pass
        return time.perf_counter() - t0

    per_call = (min(loop_span() for _ in range(5))
                - min(loop_empty() for _ in range(5))) / n
    assert per_call < 2e-6, \
        f"disabled span costs {per_call * 1e9:.0f}ns/call (budget 2000)"


# -- metrics registry ------------------------------------------------------
def test_registry_counters_gauges_histograms():
    reg = MetricsRegistry()
    reg.add("c", 2)
    reg.add("c", 3)
    reg.gauge("g").set(1.5)
    reg.gauge("g").set_info({"chosen": "cpu"})
    for v in range(100):
        reg.observe("h", float(v))
    snap = reg.snapshot()
    assert snap["counters"]["c"] == 5
    assert snap["gauges"]["g"] == {"value": 1.5,
                                   "info": {"chosen": "cpu"}}
    h = snap["histograms"]["h"]
    assert h["count"] == 100 and h["min"] == 0.0 and h["max"] == 99.0
    assert 45 <= h["p50"] <= 55 and 90 <= h["p95"] <= 99
    assert h["p99"] >= h["p95"] >= h["p50"]


def test_registry_thread_safety():
    reg = MetricsRegistry()

    def work():
        for _ in range(10_000):
            reg.add("n")

    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert reg.value("n") == 40_000


def test_run_scope_push_pop():
    base = obs.metrics()
    robs = obs.start_run()
    assert obs.metrics() is robs.registry
    assert obs.metrics() is not base
    obs.metrics().add("phase/x_sec", 1.0)
    extra = {}
    obs.publish_stats_extra(extra)
    assert extra["x_sec"] == 1.0
    obs.finish_run(robs)
    assert obs.metrics() is base
    assert not obs.tracer().enabled


# -- exports ---------------------------------------------------------------
def test_chrome_trace_event_format(tmp_path):
    tr = Tracer(enabled=True)
    tr.name_thread("main-test")
    with tr.span("outer"):
        with tr.span("inner", rows=3) as sp:
            sp.event("marker", x=1)
    path = tmp_path / "trace.json"
    obs.write_chrome_trace(tr, str(path))
    blob = json.loads(path.read_text())
    events = blob["traceEvents"]
    assert isinstance(events, list) and events
    complete = [e for e in events if e["ph"] == "X"]
    assert {e["name"] for e in complete} == {"outer", "inner"}
    for e in complete:
        assert isinstance(e["ts"], float) and isinstance(e["dur"], float)
        assert e["dur"] >= 0 and "pid" in e and "tid" in e
    instants = [e for e in events if e["ph"] == "i"]
    assert any(e["name"] == "marker" and e["args"] == {"x": 1}
               for e in instants)
    metas = [e for e in events if e["ph"] == "M"]
    assert any(e["args"]["name"] == "main-test" for e in metas)
    # sorted by timestamp (Perfetto requires no particular order, but
    # sortedness makes the artifact diffable)
    ts = [e.get("ts", 0.0) for e in events]
    assert ts == sorted(ts)


def test_metrics_jsonl_roundtrip(tmp_path):
    reg = MetricsRegistry()
    reg.add("phase/vote_sec", 0.25)
    reg.gauge("dispatch/tail").set_info({"chosen": "device"})
    reg.observe("pileup/slab_sec/scatter", 0.1)
    path = tmp_path / "m.jsonl"
    obs.write_metrics_jsonl(reg, str(path), meta={"backend": "jax"})
    rows = read_metrics_jsonl(str(path))
    assert rows[0]["kind"] == "meta" and rows[0]["backend"] == "jax"
    kinds = {r["kind"] for r in rows}
    assert kinds == {"meta", "counter", "gauge", "histogram"}
    gauge = next(r for r in rows if r["kind"] == "gauge")
    assert gauge["info"] == {"chosen": "device"}


# -- end-to-end: the pipeline's span tree + compat view --------------------
@pytest.mark.parametrize("pileup", ["auto", "scatter"])
def test_backend_trace_and_metrics(tmp_path, pileup):
    from sam2consensus_tpu.backends.jax_backend import JaxBackend
    from sam2consensus_tpu.config import RunConfig
    from sam2consensus_tpu.io.sam import ReadStream, read_header
    from sam2consensus_tpu.utils.simulate import SimSpec, simulate

    text = simulate(SimSpec(n_contigs=2, contig_len=200, n_reads=300,
                            read_len=40, ins_read_rate=0.2,
                            del_read_rate=0.1, seed=11))
    trace_path = tmp_path / f"trace_{pileup}.json"
    metrics_path = tmp_path / f"metrics_{pileup}.jsonl"
    cfg = RunConfig(prefix="t", backend="jax", pileup=pileup,
                    trace_out=str(trace_path),
                    metrics_out=str(metrics_path))
    handle = io.StringIO(text)
    contigs, _n, first = read_header(handle)
    res = JaxBackend().run(contigs, ReadStream(handle, first), cfg)

    blob = json.loads(trace_path.read_text())
    names = {e["name"] for e in blob["traceEvents"] if e["ph"] == "X"}
    expect = {"decode", "accumulate", "vote", "insertions", "render"}
    assert expect <= names, names
    if pileup == "scatter":
        # device pileup: staged transfers, per-slab spans, and the
        # tracing-forced accumulate barrier all appear
        assert {"pileup_dispatch", "slab", "accumulate_sync"} <= names

    rows = read_metrics_jsonl(str(metrics_path))
    counters = {r["name"]: r["value"] for r in rows
                if r["kind"] == "counter"}
    assert counters["reads/mapped"] == res.stats.reads_mapped
    assert counters["pileup/cells"] == res.stats.aligned_bases
    # the stats.extra compat view equals the registry's rounded values
    for key in ("accumulate_sec", "vote_sec", "insertions_sec",
                "render_sec"):
        assert res.stats.extra[key] == round(
            counters[f"phase/{key}"], 4)
    gauges = {r["name"]: r for r in rows if r["kind"] == "gauge"}
    assert "dispatch/pileup" in gauges
    assert res.stats.extra["pileup_path"] == \
        gauges["dispatch/pileup"]["info"]


# -- export edge cases -----------------------------------------------------
def test_export_empty_registry_and_tracer(tmp_path):
    """An empty run still produces schema-valid artifacts."""
    reg = MetricsRegistry()
    mpath = tmp_path / "empty.jsonl"
    obs.write_metrics_jsonl(reg, str(mpath))
    rows = read_metrics_jsonl(str(mpath))
    assert len(rows) == 1 and rows[0]["kind"] == "meta"

    tr = Tracer(enabled=True)          # enabled, but nothing recorded
    tpath = tmp_path / "empty.json"
    obs.write_chrome_trace(tr, str(tpath))
    blob = json.loads(tpath.read_text())
    assert blob["traceEvents"] == []


def test_export_unicode_span_labels(tmp_path):
    tr = Tracer(enabled=True)
    tr.name_thread("décode-λ")
    with tr.span("φάση/vote", note="naïve—çedilla"):
        pass
    tr.event("drift/σ", chosen="gén")
    path = tmp_path / "uni.json"
    obs.write_chrome_trace(tr, str(path))
    blob = json.loads(path.read_text(encoding="utf-8"))
    names = {e["name"] for e in blob["traceEvents"]}
    assert "φάση/vote" in names and "drift/σ" in names
    complete = [e for e in blob["traceEvents"] if e["ph"] == "X"]
    assert complete[0]["args"]["note"] == "naïve—çedilla"


def test_export_concurrent_with_recording(tmp_path):
    """Exports taken WHILE other threads record stay schema-valid
    (drain/snapshot are locked snapshots, not live views)."""
    tr = Tracer(enabled=True)
    reg = MetricsRegistry()
    stop = threading.Event()

    def hammer():
        # paced, not free-spinning: on a 1-core host three unthrottled
        # recording threads starve the exporting main thread (the GIL
        # round-robins ~75% of cycles to them) and the event buffer
        # outgrows each export pass — a livelock that timed out the
        # whole suite.  The property under test is schema validity of
        # exports taken WHILE other threads record, which a paced
        # recorder exercises identically.
        i = 0
        while not stop.is_set():
            with tr.span("hot", i=i):
                reg.add("phase/hot_sec", 1e-6)
                reg.observe("h", float(i % 7))
            i += 1
            if i % 64 == 0:
                stop.wait(0.001)

    workers = [threading.Thread(target=hammer) for _ in range(3)]
    for w in workers:
        w.start()
    try:
        for k in range(5):
            tpath = tmp_path / f"t{k}.json"
            mpath = tmp_path / f"m{k}.jsonl"
            obs.write_chrome_trace(tr, str(tpath))
            obs.write_metrics_jsonl(reg, str(mpath))
            blob = json.loads(tpath.read_text())
            for e in blob["traceEvents"]:
                assert e["ph"] in ("X", "i", "M")
                if e["ph"] == "X":
                    assert e["dur"] >= 0
            for row in read_metrics_jsonl(str(mpath)):
                assert "kind" in row
    finally:
        stop.set()
        for w in workers:
            w.join()


def test_export_numpy_args_serializable(tmp_path):
    """numpy scalars riding in span args / gauge info must not turn an
    artifact write into a crash."""
    import numpy as np

    tr = Tracer(enabled=True)
    with tr.span("s", n=np.int64(7), f=np.float32(0.5)):
        pass
    reg = MetricsRegistry()
    reg.gauge("g").set_info({"rows": np.int32(3),
                             "arr": np.arange(2)})
    obs.write_chrome_trace(tr, str(tmp_path / "t.json"))
    obs.write_metrics_jsonl(reg, str(tmp_path / "m.jsonl"))
    blob = json.loads((tmp_path / "t.json").read_text())
    (span,) = [e for e in blob["traceEvents"] if e["ph"] == "X"]
    assert span["args"]["n"] == 7
    g = next(r for r in read_metrics_jsonl(str(tmp_path / "m.jsonl"))
             if r["kind"] == "gauge")
    assert g["info"]["rows"] == 3 and g["info"]["arr"] == [0, 1]


# -- decision ledger -------------------------------------------------------
def test_ledger_residual_join_and_gauges():
    robs = obs.start_run()
    try:
        obs.record_decision(
            "tail_placement", "cpu",
            inputs={"total_len": 1000},
            predicted={"sec": 0.10},
            alternatives={"cpu": 0.10, "device": 0.30},
            measured={"sec": {"counters": ["phase/vote_sec"]}})
        obs.metrics().add("phase/vote_sec", 0.12)   # within band
        recs = obs.finalize_decisions()
        (rec,) = [r for r in recs if r.decision == "tail_placement"]
        assert rec.measured["sec"] == pytest.approx(0.12)
        assert rec.residual["sec"] == pytest.approx(1.2)
        assert not rec.drift
        snap = robs.registry.snapshot()
        assert snap["gauges"]["residual/tail_placement/sec"]["value"] \
            == pytest.approx(1.2)
        info = snap["gauges"]["residual/tail_placement"]["info"]
        assert info["chosen"] == "cpu" and info["drift"] is False
        assert "drift/events" not in snap["counters"]
    finally:
        obs.finish_run(robs)


def test_ledger_drift_fires_outside_band():
    robs = obs.start_run()
    try:
        obs.record_decision(
            "link_constants", "default",
            predicted={"bps": 40e6},
            measured={"bps": {"num": ["wire/bytes"],
                              "den": ["phase/stage_sec"]}})
        # measured effective rate 10x under the modeled one (the
        # round-5 drifted-default shape): 4 MB over 1 s vs 40 MB/s
        obs.metrics().add("wire/bytes", 4e6)
        obs.metrics().add("phase/stage_sec", 1.0)
        recs = obs.finalize_decisions()
        (rec,) = [r for r in recs if r.decision == "link_constants"]
        assert rec.residual["bps"] == pytest.approx(0.1)
        assert rec.drift
        snap = robs.registry.snapshot()
        assert snap["counters"]["drift/events"] == 1
        assert "drift/link_constants" in snap["gauges"]
        extra = {}
        obs.publish_stats_extra(extra)
        assert extra["drift/events"] == 1
        assert extra["residual/link_constants/bps"] == pytest.approx(0.1)
    finally:
        obs.finish_run(robs)


def test_ledger_drift_respects_sec_floor_and_band_zero():
    robs = obs.start_run()
    try:
        # microsecond predictions never drift (noise, not mis-routes)
        obs.record_decision(
            "tiny", "x", predicted={"sec": 1e-5},
            measured={"sec": {"counters": ["phase/a_sec"]}})
        obs.metrics().add("phase/a_sec", 1e-3)      # 100x, but tiny
        # band=0 decisions record residual but never drift
        obs.record_decision(
            "informational", "y", predicted={"sec": 0.1},
            measured={"sec": {"counters": ["phase/b_sec"]}}, band=0)
        obs.metrics().add("phase/b_sec", 100.0)     # 1000x
        recs = {r.decision: r for r in obs.finalize_decisions()}
        assert not recs["tiny"].drift
        assert recs["informational"].residual["sec"] == pytest.approx(
            1000.0)
        assert not recs["informational"].drift
        assert "drift/events" not in robs.registry.snapshot()["counters"]
    finally:
        obs.finish_run(robs)


def test_ledger_last_wins_and_missing_measurements():
    robs = obs.start_run()
    try:
        obs.record_decision("d", "first", predicted={"sec": 1.0})
        obs.record_decision(
            "d", "second", predicted={"sec": 2.0},
            measured={"sec": {"counters": ["phase/never_sec"]},
                      "bps": {"num": ["wire/bytes"],
                              "den": ["phase/zero_sec"]}})
        recs = obs.finalize_decisions()
        (rec,) = [r for r in recs if r.decision == "d"]
        assert rec.chosen == "second"
        # absent counters / zero denominators join nothing — and
        # therefore can never fabricate a drift
        assert rec.measured == {} and rec.residual == {}
        assert not rec.drift
    finally:
        obs.finish_run(robs)


def test_ledger_zero_traffic_and_min_num_never_drift():
    """A zero rate is the ABSENCE of a measurement: num == 0 (no wire
    traffic despite elapsed windows) and sub-floor traffic (min_num)
    both join nothing — a healthy host-routed run must never alarm."""
    robs = obs.start_run()
    try:
        obs.record_decision(
            "link_constants", "default", predicted={"bps": 40e6},
            measured={"bps": {"num": ["wire/bytes"],
                              "den": ["phase/pileup_dispatch_sec"]}})
        obs.metrics().add("phase/pileup_dispatch_sec", 3.0)  # no bytes
        obs.record_decision(
            "wire_codec", "delta8", predicted={"bps": 40e6},
            measured={"bps": {"num": ["wire/bytes2"],
                              "den": ["phase/stage_sec"],
                              "min_num": 8e6}})
        obs.metrics().add("wire/bytes2", 2e6)       # under the floor
        obs.metrics().add("phase/stage_sec", 5.0)   # compute-dominated
        recs = {r.decision: r for r in obs.finalize_decisions()}
        assert recs["link_constants"].measured == {}
        assert recs["wire_codec"].measured == {}
        assert not recs["link_constants"].drift
        assert not recs["wire_codec"].drift
        assert "drift/events" not in robs.registry.snapshot()["counters"]
    finally:
        obs.finish_run(robs)


def test_link_constants_mixed_env_probe_provenance(monkeypatch):
    """One env override + a probed other half must be labeled env+…,
    not attributed wholesale to the probe."""
    import jax

    from sam2consensus_tpu.backends import jax_backend as jb
    from sam2consensus_tpu.utils import linkprobe

    monkeypatch.setenv("S2C_TAIL_RT_MS", "100")
    monkeypatch.delenv("S2C_TAIL_LINK_MBPS", raising=False)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(linkprobe, "probe_link",
                        lambda force=False: (5e-4, 10e9))
    robs = obs.start_run()
    try:
        assert jb._link_constants() == (0.1, 10e9)
        rec = obs.ledger().get("link_constants")
        assert rec.chosen.startswith("env+")
    finally:
        obs.finish_run(robs)


def test_env_forced_drifted_link_constant_triggers_drift(monkeypatch):
    """The acceptance pin: a drifted env-forced constant produces a
    drift event through the REAL decision site (_tail_cpu_wins with
    the model predicting a ~ms device tail that 'measures' seconds)."""
    import jax

    from sam2consensus_tpu.backends import jax_backend as jb

    # absurdly fast modeled link -> the model predicts a ~0.4 ms device
    # tail and routes there
    monkeypatch.setenv("S2C_TAIL_RT_MS", "0.1")
    monkeypatch.setenv("S2C_TAIL_LINK_MBPS", "40000")
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    robs = obs.start_run()
    try:
        cpu_won = jb._tail_cpu_wins(total_len=1_000_000, n_thresholds=1,
                                    upload_bytes=6_000_000,
                                    native_tail=True)
        assert not cpu_won                       # model chose the chip
        # ...but the measured tail took 2 s (the link was NOT 40 GB/s)
        obs.metrics().add("phase/vote_sec", 2.0)
        recs = {r.decision: r for r in obs.finalize_decisions()}
        rec = recs["tail_placement"]
        assert rec.chosen == "device" and rec.drift
        assert rec.residual["sec"] > 100
        snap = robs.registry.snapshot()
        assert snap["counters"]["drift/events"] >= 1
        assert "drift/tail_placement" in snap["gauges"]
    finally:
        obs.finish_run(robs)


# -- manifest --------------------------------------------------------------
def test_manifest_written_alongside_metrics_out(tmp_path):
    """End-to-end: a sharded device-pileup run under --metrics-out
    yields a manifest where the auto decisions carry prediction,
    measured outcome and residual, plus provenance + artifact hashes."""
    from sam2consensus_tpu.backends.jax_backend import JaxBackend
    from sam2consensus_tpu.config import RunConfig
    from sam2consensus_tpu.io.sam import ReadStream, read_header
    from sam2consensus_tpu.observability import manifest as man_mod
    from sam2consensus_tpu.utils.simulate import SimSpec, simulate

    text = simulate(SimSpec(n_contigs=2, contig_len=300, n_reads=400,
                            read_len=40, ins_read_rate=0.1, seed=9))
    mpath = tmp_path / "run.jsonl"
    cfg = RunConfig(prefix="t", backend="jax", pileup="scatter",
                    shards=2, metrics_out=str(mpath))
    handle = io.StringIO(text)
    contigs, _n, first = read_header(handle)
    JaxBackend().run(contigs, ReadStream(handle, first), cfg)

    man_path = man_mod.manifest_path_for(str(mpath))
    man = json.loads(open(man_path).read())
    assert man["schema"] == "s2c-manifest/1"
    assert man["config"]["pileup"] == "scatter"
    assert man["env_overrides"].get("JAX_PLATFORMS") == "cpu"
    decisions = {d["decision"]: d for d in man["decisions"]}
    # the run's auto decisions are all present...
    assert {"wire_codec", "shard_mode", "tail_placement",
            "link_constants"} <= set(decisions)
    # ...and the priced ones carry prediction + measured + residual
    wire = decisions["wire_codec"]
    assert wire["predicted"]["ratio"] == 1.0       # packed5 (link-free)
    assert wire["measured"]["ratio"] == pytest.approx(1.0)
    assert wire["residual"]["ratio"] == pytest.approx(1.0)
    shard = decisions["shard_mode"]
    assert shard["chosen"] in ("dp", "sp", "dpsp")
    assert shard["predicted"]["sec"] > 0
    assert shard["measured"]["sec"] > 0
    assert shard["residual"]["sec"] > 0
    assert shard["alternatives"]                  # the full cost table
    assert not shard["drift"]                     # band=0: informational
    # artifact hash matches the metrics file the same run wrote
    digest = man["artifacts"]["metrics"]["digest"]
    assert digest == man_mod.file_digest(str(mpath))
    assert man["phases"].get("phase/vote_sec", 0) > 0
    # the same manifest is reachable in-process (bench.py embeds it)
    last = obs.last_manifest()
    assert last is not None and last["schema"] == "s2c-manifest/1"


def test_manifest_summarize_compact():
    from sam2consensus_tpu.observability import manifest as man_mod

    robs = obs.start_run()
    try:
        obs.record_decision("wire_codec", "delta8",
                            predicted={"ratio": 2.0})
    finally:
        obs.finish_run(robs)
    summary = man_mod.summarize(obs.last_manifest())
    assert summary["schema"] == "s2c-manifest/1"
    assert summary["decisions"][0]["decision"] == "wire_codec"
    assert "config" not in summary                 # compact form


def test_tail_dispatch_decision_recorded():
    """The placement model's verdict carries its modeled inputs."""
    from sam2consensus_tpu.backends import jax_backend as jb

    robs = obs.start_run()
    try:
        jb._tail_cpu_wins(total_len=10_000, n_thresholds=1,
                          upload_bytes=60_000, native_tail=False)
        snap = robs.registry.snapshot()
        info = snap["gauges"]["dispatch/tail"]["info"]
        assert info["chosen"] in ("cpu", "device")
        for k in ("cpu_sec", "chip_sec", "rt_sec", "link_bps",
                  "upload_bytes", "total_len"):
            assert k in info
    finally:
        obs.finish_run(robs)
