"""The observability subsystem: spans, metrics, exports, compat view.

Pins the tentpole contracts (the ISSUE's acceptance list):

* span nesting/ordering and thread isolation (each thread's spans carry
  its own tid while landing in one shared list);
* disabled-mode no-op: the tracer adds < 2% to a tight loop when off;
* exported Chrome trace JSON is valid trace-event format (``ph``,
  ``ts``, ``dur``, ``pid``/``tid`` on every complete event);
* a full jax-backend run under ``--trace-out`` produces the pipeline
  span tree and a metrics JSONL whose phase counters agree with the
  legacy ``stats.extra`` compat view bench.py reads.
"""

import io
import json
import threading
import time

import pytest

from sam2consensus_tpu import observability as obs
from sam2consensus_tpu.observability.export import (chrome_trace_events,
                                                    read_metrics_jsonl)
from sam2consensus_tpu.observability.metrics import MetricsRegistry
from sam2consensus_tpu.observability.trace import Tracer


# -- tracer core -----------------------------------------------------------
def test_span_nesting_and_ordering():
    tr = Tracer(enabled=True)
    with tr.span("outer", kind="phase"):
        time.sleep(0.002)
        with tr.span("inner"):
            time.sleep(0.001)
    spans = {s.name: s for s in tr.drain()}
    outer, inner = spans["outer"], spans["inner"]
    # inner closed first (recorded first), nested strictly inside outer
    assert [s.name for s in tr.drain()] == ["inner", "outer"]
    assert outer.ts_us <= inner.ts_us
    assert inner.ts_us + inner.dur_us <= outer.ts_us + outer.dur_us + 1.0
    assert outer.args == {"kind": "phase"}


def test_span_events_and_args():
    tr = Tracer(enabled=True)
    with tr.span("phase") as sp:
        sp.event("decision", chosen="cpu", cpu_sec=0.1)
        sp.set_args(rows=7)
    (s,) = tr.drain()
    assert s.args == {"rows": 7}
    (name, ts, args) = s.events[0]
    assert name == "decision" and args["chosen"] == "cpu"
    assert s.ts_us <= ts <= s.ts_us + s.dur_us


def test_span_sync_runs_inside_span():
    tr = Tracer(enabled=True)
    ran = []
    with tr.span("device", sync=lambda: (time.sleep(0.003),
                                         ran.append(True))):
        pass
    (s,) = tr.drain()
    assert ran == [True]
    assert s.dur_us >= 2000  # the sync's sleep is inside the duration


def test_tracer_thread_safety():
    tr = Tracer(enabled=True)
    # the barrier holds every worker alive until all have started, so
    # thread idents cannot be reused (a finished thread's ident may be
    # recycled by the OS) and the 4-distinct-tids assertion is sound
    gate = threading.Barrier(4)

    def work(i):
        gate.wait()
        for k in range(50):
            with tr.span(f"t{i}", k=k):
                pass

    threads = [threading.Thread(target=work, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    spans = tr.drain()
    assert len(spans) == 200
    # each thread's spans carry its own tid; 4 distinct tids
    assert len({s.tid for s in spans}) == 4
    for name in ("t0", "t1", "t2", "t3"):
        assert sum(1 for s in spans if s.name == name) == 50


def test_disabled_tracer_is_noop_and_cheap():
    tr = Tracer(enabled=False)
    with tr.span("x") as sp:
        sp.event("e", a=1)
        sp.set_args(b=2)
    tr.event("top")
    assert tr.drain() == []

    # The < 2% budget, asserted per call: a wall-clock A/B of two loops
    # cannot resolve 2% on a shared CI host (measured noise floor here
    # is ~±10% even on 250 us bodies), so pin the absolute no-op cost
    # instead.  The real hot paths call span() once per BATCH/SLAB —
    # units of >= 100 us of work (one device dispatch ~ms, one decode
    # batch ~10 ms) — so < 2 us per disabled call IS < 2% overhead on
    # the tightest loop that actually exists, with a big margin held
    # back for slower hosts (measured ~0.5 us/call).
    n = 50_000

    def loop_span():
        t0 = time.perf_counter()
        for _ in range(n):
            with tr.span("hot"):
                pass
        return time.perf_counter() - t0

    def loop_empty():
        t0 = time.perf_counter()
        for _ in range(n):
            pass
        return time.perf_counter() - t0

    per_call = (min(loop_span() for _ in range(5))
                - min(loop_empty() for _ in range(5))) / n
    assert per_call < 2e-6, \
        f"disabled span costs {per_call * 1e9:.0f}ns/call (budget 2000)"


# -- metrics registry ------------------------------------------------------
def test_registry_counters_gauges_histograms():
    reg = MetricsRegistry()
    reg.add("c", 2)
    reg.add("c", 3)
    reg.gauge("g").set(1.5)
    reg.gauge("g").set_info({"chosen": "cpu"})
    for v in range(100):
        reg.observe("h", float(v))
    snap = reg.snapshot()
    assert snap["counters"]["c"] == 5
    assert snap["gauges"]["g"] == {"value": 1.5,
                                   "info": {"chosen": "cpu"}}
    h = snap["histograms"]["h"]
    assert h["count"] == 100 and h["min"] == 0.0 and h["max"] == 99.0
    assert 45 <= h["p50"] <= 55 and 90 <= h["p95"] <= 99
    assert h["p99"] >= h["p95"] >= h["p50"]


def test_registry_thread_safety():
    reg = MetricsRegistry()

    def work():
        for _ in range(10_000):
            reg.add("n")

    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert reg.value("n") == 40_000


def test_run_scope_push_pop():
    base = obs.metrics()
    robs = obs.start_run()
    assert obs.metrics() is robs.registry
    assert obs.metrics() is not base
    obs.metrics().add("phase/x_sec", 1.0)
    extra = {}
    obs.publish_stats_extra(extra)
    assert extra["x_sec"] == 1.0
    obs.finish_run(robs)
    assert obs.metrics() is base
    assert not obs.tracer().enabled


# -- exports ---------------------------------------------------------------
def test_chrome_trace_event_format(tmp_path):
    tr = Tracer(enabled=True)
    tr.name_thread("main-test")
    with tr.span("outer"):
        with tr.span("inner", rows=3) as sp:
            sp.event("marker", x=1)
    path = tmp_path / "trace.json"
    obs.write_chrome_trace(tr, str(path))
    blob = json.loads(path.read_text())
    events = blob["traceEvents"]
    assert isinstance(events, list) and events
    complete = [e for e in events if e["ph"] == "X"]
    assert {e["name"] for e in complete} == {"outer", "inner"}
    for e in complete:
        assert isinstance(e["ts"], float) and isinstance(e["dur"], float)
        assert e["dur"] >= 0 and "pid" in e and "tid" in e
    instants = [e for e in events if e["ph"] == "i"]
    assert any(e["name"] == "marker" and e["args"] == {"x": 1}
               for e in instants)
    metas = [e for e in events if e["ph"] == "M"]
    assert any(e["args"]["name"] == "main-test" for e in metas)
    # sorted by timestamp (Perfetto requires no particular order, but
    # sortedness makes the artifact diffable)
    ts = [e.get("ts", 0.0) for e in events]
    assert ts == sorted(ts)


def test_metrics_jsonl_roundtrip(tmp_path):
    reg = MetricsRegistry()
    reg.add("phase/vote_sec", 0.25)
    reg.gauge("dispatch/tail").set_info({"chosen": "device"})
    reg.observe("pileup/slab_sec/scatter", 0.1)
    path = tmp_path / "m.jsonl"
    obs.write_metrics_jsonl(reg, str(path), meta={"backend": "jax"})
    rows = read_metrics_jsonl(str(path))
    assert rows[0]["kind"] == "meta" and rows[0]["backend"] == "jax"
    kinds = {r["kind"] for r in rows}
    assert kinds == {"meta", "counter", "gauge", "histogram"}
    gauge = next(r for r in rows if r["kind"] == "gauge")
    assert gauge["info"] == {"chosen": "device"}


# -- end-to-end: the pipeline's span tree + compat view --------------------
@pytest.mark.parametrize("pileup", ["auto", "scatter"])
def test_backend_trace_and_metrics(tmp_path, pileup):
    from sam2consensus_tpu.backends.jax_backend import JaxBackend
    from sam2consensus_tpu.config import RunConfig
    from sam2consensus_tpu.io.sam import ReadStream, read_header
    from sam2consensus_tpu.utils.simulate import SimSpec, simulate

    text = simulate(SimSpec(n_contigs=2, contig_len=200, n_reads=300,
                            read_len=40, ins_read_rate=0.2,
                            del_read_rate=0.1, seed=11))
    trace_path = tmp_path / f"trace_{pileup}.json"
    metrics_path = tmp_path / f"metrics_{pileup}.jsonl"
    cfg = RunConfig(prefix="t", backend="jax", pileup=pileup,
                    trace_out=str(trace_path),
                    metrics_out=str(metrics_path))
    handle = io.StringIO(text)
    contigs, _n, first = read_header(handle)
    res = JaxBackend().run(contigs, ReadStream(handle, first), cfg)

    blob = json.loads(trace_path.read_text())
    names = {e["name"] for e in blob["traceEvents"] if e["ph"] == "X"}
    expect = {"decode", "accumulate", "vote", "insertions", "render"}
    assert expect <= names, names
    if pileup == "scatter":
        # device pileup: staged transfers, per-slab spans, and the
        # tracing-forced accumulate barrier all appear
        assert {"pileup_dispatch", "slab", "accumulate_sync"} <= names

    rows = read_metrics_jsonl(str(metrics_path))
    counters = {r["name"]: r["value"] for r in rows
                if r["kind"] == "counter"}
    assert counters["reads/mapped"] == res.stats.reads_mapped
    assert counters["pileup/cells"] == res.stats.aligned_bases
    # the stats.extra compat view equals the registry's rounded values
    for key in ("accumulate_sec", "vote_sec", "insertions_sec",
                "render_sec"):
        assert res.stats.extra[key] == round(
            counters[f"phase/{key}"], 4)
    gauges = {r["name"]: r for r in rows if r["kind"] == "gauge"}
    assert "dispatch/pileup" in gauges
    assert res.stats.extra["pileup_path"] == \
        gauges["dispatch/pileup"]["info"]


def test_tail_dispatch_decision_recorded():
    """The placement model's verdict carries its modeled inputs."""
    from sam2consensus_tpu.backends import jax_backend as jb

    robs = obs.start_run()
    try:
        jb._tail_cpu_wins(total_len=10_000, n_thresholds=1,
                          upload_bytes=60_000, native_tail=False)
        snap = robs.registry.snapshot()
        info = snap["gauges"]["dispatch/tail"]["info"]
        assert info["chosen"] in ("cpu", "device")
        for k in ("cpu_sec", "chip_sec", "rt_sec", "link_bps",
                  "upload_bytes", "total_len"):
            assert k in info
    finally:
        obs.finish_run(robs)
