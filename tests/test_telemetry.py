"""Fleet telemetry plane (observability/telemetry.py + serve wiring).

The ISSUE-10 acceptance pins live here:

* a journaled 8-job two-tenant serve queue with one ``job_hang``-
  injected job shows the exposition rewritten MID-HANG (heartbeat-
  aged, not job-boundary-stale — the stale-health-while-hung fix),
  per-tenant e2e/queue_wait p50/p99 present for BOTH tenants,
  ``slo/violations`` burned exactly for the hung job's tenant, and an
  on-demand profiler capture produced during the hang;
* byte-identical consensus output with telemetry enabled vs disabled;
* the OpenMetrics exposition of a real 4-job serve queue passes the
  promtool-style format lint, including counter monotonicity across
  two scrapes and over the live HTTP endpoint.
"""

import importlib.util
import json
import os
import threading
import time
import urllib.request

import pytest

from sam2consensus_tpu.config import RunConfig
from sam2consensus_tpu.io.fasta import render_file
from sam2consensus_tpu.observability import telemetry as T
from sam2consensus_tpu.observability.metrics import (HIST_CAP, Histogram,
                                                     MetricsRegistry)
from sam2consensus_tpu.utils.simulate import SimSpec, simulate

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _no_persistent_cache(monkeypatch):
    monkeypatch.setenv("S2C_JIT_CACHE", "")


def _sim(tmp, name, seed, contig_len=3000, n_reads=1000):
    spec = SimSpec(n_contigs=1, contig_len=contig_len, n_reads=n_reads,
                   read_len=100, contig_len_jitter=0.0, seed=seed,
                   contig_prefix="tele")
    path = os.path.join(str(tmp), name)
    with open(path, "w") as fh:
        fh.write(simulate(spec))
    return path


def _runner(**kw):
    from sam2consensus_tpu.serve import ServeRunner

    kw.setdefault("prewarm", "off")
    kw.setdefault("persistent_cache", False)
    return ServeRunner(**kw)


BASE = dict(backend="jax", pileup="scatter", shards=1)


# =========================================================================
# units: SLO grammar
# =========================================================================
def test_parse_slo_grammar():
    assert T.parse_slo("e2e=5s,queue=1s") == {"e2e": 5.0,
                                              "queue_wait": 1.0}
    assert T.parse_slo("queue_wait=250ms, decode=0.5") == {
        "queue_wait": 0.25, "decode": 0.5}
    assert T.parse_slo("DISPATCH=2s") == {"dispatch": 2.0}
    assert T.parse_slo("") == {}
    assert T.parse_slo(None) == {}
    for bad in ("bogus=1s", "e2e", "e2e=zap", "e2e=0", "e2e=-1s"):
        with pytest.raises(ValueError):
            T.parse_slo(bad)


def test_parse_slo_env_fallback(monkeypatch):
    monkeypatch.setenv("S2C_SLO", "e2e=3s")
    assert T.parse_slo(None) == {"e2e": 3.0}
    assert T.parse_slo("vote=1s") == {"vote": 1.0}   # explicit wins


# =========================================================================
# units: histogram merge + aggregate fold
# =========================================================================
def test_histogram_merge_exact_stats():
    a, b = Histogram(), Histogram()
    for v in (1.0, 2.0, 3.0):
        a.observe(v)
    for v in (0.5, 9.0):
        b.observe(v)
    a.merge(b)
    assert a.count == 5
    assert a.total == pytest.approx(15.5)
    assert a.vmin == 0.5 and a.vmax == 9.0
    assert sorted(a.values) == [0.5, 1.0, 2.0, 3.0, 9.0]
    a.merge(Histogram())                 # empty merge is a no-op
    assert a.count == 5


def test_histogram_merge_past_reservoir_cap():
    a, b = Histogram(), Histogram()
    for i in range(HIST_CAP):
        a.observe(float(i))
    for i in range(HIST_CAP + 100):
        b.observe(float(i))
    a.merge(b)
    assert a.count == HIST_CAP + HIST_CAP + 100     # exact count
    assert len(a.values) == HIST_CAP                # bounded reservoir
    assert a.vmax == float(HIST_CAP + 99)


def test_aggregate_fold_counters_gauges_histograms():
    agg = T.AggregateRegistry()
    agg.add("phase/decode_sec", 1.0)
    agg.add("serve/jobs", 3)             # runner-owned at server scope
    job = MetricsRegistry()
    job.add("phase/decode_sec", 2.0)
    job.add("serve/overlap_sec", 9.0)    # must NOT double-count
    job.gauge("wire/codec").set(1.0)
    job.gauge("wire/codec").set_info({"chosen": "delta8"})
    job.observe("pileup/slab_sec", 0.25)
    agg.fold(job, job_id="j1", tenant="ta")
    assert agg.value("phase/decode_sec") == pytest.approx(3.0)
    assert agg.value("serve/overlap_sec") == 0.0
    assert agg.value("serve/jobs") == 3
    info = agg.info("wire/codec")
    assert info["folded_from"] == "j1" and info["tenant"] == "ta"
    assert info["chosen"] == "delta8" and "updated_unix" in info
    snap = agg.snapshot()
    assert snap["histograms"]["pileup/slab_sec"]["count"] == 1
    assert agg.value("telemetry/jobs_folded") == 1
    # second fold keeps summing
    agg.fold(job, job_id="j2")
    assert agg.value("phase/decode_sec") == pytest.approx(5.0)
    assert snap is not agg.snapshot()


# =========================================================================
# units: exposition render + lint
# =========================================================================
def _demo_registry():
    r = T.AggregateRegistry()
    r.add("phase/decode_sec", 1.5)
    r.add("serve/jobs", 4)
    r.add("slo/violations/ta/e2e", 1)
    r.gauge("serve/heartbeat_age_sec").set(0.5)
    r.observe("slo/ta/e2e", 0.25)
    r.observe("slo/ta/e2e", 0.75)
    return r


def test_render_openmetrics_structure():
    text = T.render_openmetrics(_demo_registry().snapshot())
    assert 's2c_phase_seconds_total{phase="decode"} 1.5' in text
    assert 's2c_slo_violations_total{tenant="ta",phase="e2e"} 1' in text
    assert 's2c_slo_phase_seconds{tenant="ta",phase="e2e",' \
           'quantile="0.5"}' in text
    assert 's2c_slo_phase_seconds_count{tenant="ta",phase="e2e"} 2' \
        in text
    assert text.rstrip().endswith("# EOF")
    # one TYPE per family, HELP before TYPE, deterministic output
    assert text.count("# TYPE s2c_slo_phase_seconds summary") == 1
    assert text == T.render_openmetrics(_demo_registry().snapshot())
    assert T.lint_openmetrics(text) == []


def test_render_escapes_label_values():
    r = T.AggregateRegistry()
    r.observe('slo/we"ird\\ten\nant/e2e', 1.0)
    text = T.render_openmetrics(r.snapshot())
    assert T.lint_openmetrics(text) == []
    samples = T.parse_openmetrics(text)
    tenants = {s["labels"].get("tenant") for s in samples
               if "tenant" in s["labels"]}
    assert 'we"ird\\ten\nant' in tenants   # round-trips exactly


def test_lint_catches_synthetic_violations():
    def errs(text):
        return T.lint_openmetrics(text)

    # name charset
    assert errs("# TYPE s2c-bad gauge\n# EOF\n")
    # sample without TYPE
    assert any("no preceding TYPE" in e
               for e in errs("s2c_x_total 1\n# EOF\n"))
    # duplicate TYPE
    bad = ("# TYPE s2c_x gauge\n# TYPE s2c_x gauge\ns2c_x 1\n# EOF\n")
    assert any("duplicate TYPE" in e for e in errs(bad))
    # TYPE after samples
    bad = ("# TYPE s2c_x gauge\ns2c_x 1\n"
           "# TYPE s2c_y gauge\ns2c_y 1\n"
           "# TYPE s2c_x gauge\n# EOF\n")
    assert any("duplicate TYPE" in e for e in errs(bad))
    # counter without _total
    bad = "# TYPE s2c_x counter\ns2c_x 1\n# EOF\n"
    assert any("_total" in e for e in errs(bad))
    # negative counter
    bad = "# TYPE s2c_x_total counter\ns2c_x_total -1\n# EOF\n"
    assert any("negative" in e for e in errs(bad))
    # bad escape in label value
    bad = ('# TYPE s2c_x gauge\ns2c_x{a="b\\q"} 1\n# EOF\n')
    assert any("escape" in e for e in errs(bad))
    # bad label name
    bad = ('# TYPE s2c_x gauge\ns2c_x{0a="b"} 1\n# EOF\n')
    assert errs(bad)
    # duplicate sample
    bad = ("# TYPE s2c_x gauge\ns2c_x 1\ns2c_x 2\n# EOF\n")
    assert any("duplicate sample" in e for e in errs(bad))
    # quantile out of range
    bad = ('# TYPE s2c_x summary\ns2c_x{quantile="1.5"} 1\n# EOF\n')
    assert any("quantile" in e for e in errs(bad))
    # missing EOF
    assert any("EOF" in e
               for e in errs("# TYPE s2c_x gauge\ns2c_x 1\n"))
    # unparsable value
    assert errs("# TYPE s2c_x gauge\ns2c_x zap\n# EOF\n")


def test_lint_counter_monotonicity_across_scrapes():
    a = "# TYPE s2c_x_total counter\ns2c_x_total 5\n# EOF\n"
    b = "# TYPE s2c_x_total counter\ns2c_x_total 3\n# EOF\n"
    ok = "# TYPE s2c_x_total counter\ns2c_x_total 7\n# EOF\n"
    assert T.lint_openmetrics(ok, prev=a) == []
    assert any("went backwards" in e
               for e in T.lint_openmetrics(b, prev=a))
    # summary _count is monotone too
    s1 = ("# TYPE s2c_h summary\ns2c_h_count 4\ns2c_h_sum 2.0\n# EOF\n")
    s2 = ("# TYPE s2c_h summary\ns2c_h_count 2\ns2c_h_sum 2.0\n# EOF\n")
    assert any("went backwards" in e
               for e in T.lint_openmetrics(s2, prev=s1))
    # gauges may move freely
    g1 = "# TYPE s2c_g gauge\ns2c_g 5\n# EOF\n"
    g2 = "# TYPE s2c_g gauge\ns2c_g 1\n# EOF\n"
    assert T.lint_openmetrics(g2, prev=g1) == []


def test_atomic_write_leaves_no_droppings(tmp_path):
    path = str(tmp_path / "x.prom")
    T.atomic_write_text(path, "hello\n")
    T.atomic_write_text(path, "world\n")
    assert open(path).read() == "world\n"
    assert [n for n in os.listdir(tmp_path)] == ["x.prom"]


# =========================================================================
# units: JSON logging + correlation, profiler capture
# =========================================================================
def test_json_log_formatter_correlation():
    import logging

    from sam2consensus_tpu.observability.trace import Tracer

    fmt = T.JsonLogFormatter()
    rec = logging.LogRecord("sam2consensus_tpu.test", logging.WARNING,
                            __file__, 1, "slab %d retried", (3,), None)
    T.set_log_context(job_id="job7", tenant="ta", rung="host")
    tr = Tracer(enabled=True)
    try:
        with tr.span("accumulate"):
            obj = json.loads(fmt.format(rec))
    finally:
        T.set_log_context()
    assert obj["msg"] == "slab 3 retried"
    assert obj["level"] == "warning"
    assert obj["job_id"] == "job7" and obj["tenant"] == "ta"
    assert obj["rung"] == "host" and obj["span"] == "accumulate"
    # cleared context + closed span leave no stale correlation
    obj2 = json.loads(fmt.format(rec))
    assert "job_id" not in obj2 and "span" not in obj2


def test_configure_logging_json(monkeypatch):
    import logging

    from sam2consensus_tpu import observability as obs

    logger = logging.getLogger("sam2consensus_tpu")
    old_handlers, old_level = list(logger.handlers), logger.level
    try:
        logger.handlers = []
        obs.configure_logging(None, "json")   # json implies info
        assert logger.level == logging.INFO
        assert isinstance(logger.handlers[0].formatter,
                          T.JsonLogFormatter)
        with pytest.raises(SystemExit):
            obs.configure_logging("info", "yaml")
    finally:
        logger.handlers = old_handlers
        logger.setLevel(old_level)


def test_profiler_capture_touch_file_and_span_dump(tmp_path):
    from sam2consensus_tpu.observability.trace import Tracer

    cap = T.ProfilerCapture(str(tmp_path))
    assert cap.capture() is None               # not armed
    open(cap.touch_path, "w").close()
    assert cap.pending()                       # consumed the touch file
    assert not os.path.exists(cap.touch_path)
    tr = Tracer(enabled=True)
    with tr.span("decode"):
        pass
    reg = MetricsRegistry()
    reg.add("phase/decode_sec", 1.0)
    dest = cap.capture(tracer=tr, registry=reg,
                       context={"in_flight": "j0"})
    assert dest is not None and os.path.isdir(dest)
    blob = json.load(open(os.path.join(dest, "span_dump.json")))
    assert blob["schema"] == "s2c-profile-capture/1"
    assert blob["context"]["in_flight"] == "j0"
    assert blob["threads"]                     # live thread stacks
    assert any(s["name"] == "decode" for s in blob["spans"])
    assert blob["metrics"]["counters"]["phase/decode_sec"] == 1.0
    assert cap.captures == 1 and cap.last_path == dest
    assert cap.capture() is None               # disarmed after capture
    cap.request()                              # SIGUSR2 path arms too
    assert cap.pending()


# =========================================================================
# satellites: --flame, s2c_top
# =========================================================================
def test_trace_summary_flame_collapsed_stacks(tmp_path, capsys):
    ts = _tool("trace_summary")
    spans = [
        {"ph": "X", "name": "accumulate", "ts": 0.0, "dur": 100.0,
         "tid": 1},
        {"ph": "X", "name": "pileup_dispatch", "ts": 10.0, "dur": 60.0,
         "tid": 1},
        {"ph": "X", "name": "slab", "ts": 20.0, "dur": 30.0, "tid": 1},
        {"ph": "X", "name": "decode", "ts": 0.0, "dur": 50.0, "tid": 2},
    ]
    agg = ts.collapsed_stacks(spans)
    assert agg["accumulate"] == pytest.approx(40.0)        # 100-60
    assert agg["accumulate;pileup_dispatch"] == pytest.approx(30.0)
    assert agg["accumulate;pileup_dispatch;slab"] == \
        pytest.approx(30.0)
    assert agg["decode"] == pytest.approx(50.0)
    # the CLI path over a real trace file
    trace = tmp_path / "t.json"
    trace.write_text(json.dumps({"traceEvents": spans}))
    assert ts.main([str(trace), "--flame"]) == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert "accumulate;pileup_dispatch;slab 30" in out
    assert "decode 50" in out
    # self-time totals across paths == total span self time
    total = sum(int(line.rsplit(" ", 1)[1]) for line in out)
    assert total == 150


def test_s2c_top_render_frame(tmp_path, capsys):
    top = _tool("s2c_top")
    health = {
        "schema": "s2c-health/1", "uptime_sec": 12.5, "queue_depth": 2,
        "in_flight": "job3", "in_flight_sec": 4.0,
        "last_heartbeat_age_sec": 6.5,
        "jobs": {"run": 3, "failed": 1, "watchdog_timeouts": 1},
        "admission": {"admitted": 4, "rejected": 0, "pinned": 0,
                      "poison": 0},
        "tenant_rungs": {"tb": "host"},
        "slo": {"objectives": {"e2e": 2.0}, "violations": 1,
                "burn_by_tenant": {"tb": 1}},
        "telemetry": {"profile_captures": 1, "last_profile": "/x/p1"},
    }
    text = T.render_openmetrics(_demo_registry().snapshot())
    samples = T.parse_openmetrics(text)
    lines = top.render(health, samples)
    frame = "\n".join(lines)
    assert "in-flight: job3" in frame
    assert "possible wedge" in frame          # aging heartbeat flagged
    assert "ta" in frame and "tb" in frame    # tenants from both files
    assert "violations 1" in frame
    assert "profiler captures: 1" in frame
    assert top.render(None, None) == \
        ["s2c_top: waiting for health snapshot..."]
    # --once end-to-end over real files
    hp = tmp_path / "health.json"
    hp.write_text(json.dumps(health))
    tp = tmp_path / "m.prom"
    tp.write_text(text)
    assert top.main(["--health", str(hp), "--telemetry", str(tp),
                     "--once"]) == 0
    assert "job3" in capsys.readouterr().out


# =========================================================================
# satellites: check_perf_claims accepts (and lints) telemetry artifacts
# =========================================================================
def test_check_perf_claims_lints_telemetry_artifacts(tmp_path):
    cpc = _tool("check_perf_claims")
    committed = os.path.join(
        REPO, "campaign", "serve_telemetry_r06_cpufallback.prom")
    assert os.path.exists(committed)
    assert cpc.lint_telemetry_artifact(committed) == []
    # a malformed cited exposition is flagged as a violation
    repo = tmp_path
    os.makedirs(repo / "campaign")
    (repo / "campaign" / "bad.prom").write_text("s2c_x_total 1\n")
    (repo / "PERF.md").write_text(
        "The serve path hits 5.6x vs cold, see "
        "campaign/bad.prom evidence.\n")
    viol = cpc.check_file(str(repo), "PERF.md")
    assert any("fails the OpenMetrics lint" in v for v in viol)
    # a well-formed one passes
    (repo / "campaign" / "bad.prom").write_text(
        "# TYPE s2c_x_total counter\ns2c_x_total 1\n# EOF\n")
    assert cpc.check_file(str(repo), "PERF.md") == []


# =========================================================================
# serve integration: 4-job queue exposition + endpoint (tier-1 pin)
# =========================================================================
def test_serve_queue_exposition_lint_and_endpoint(tmp_path):
    from sam2consensus_tpu.serve import JobSpec

    paths = [_sim(tmp_path, f"q{k}.sam", seed=40 + k) for k in range(4)]
    tele = str(tmp_path / "metrics.prom")
    health = str(tmp_path / "health.json")
    runner = _runner(telemetry_out=tele, telemetry_port=0,
                     health_out=health, telemetry_interval=0.05,
                     slo="e2e=120s")
    try:
        specs = [JobSpec(filename=p, config=RunConfig(**BASE),
                         tenant="ta" if k < 2 else "tb")
                 for k, p in enumerate(paths)]
        res = runner.submit_jobs(specs[:2])
        first = open(tele).read()
        assert T.lint_openmetrics(first) == []
        res += runner.submit_jobs(specs[2:])
        second = open(tele).read()
        assert all(r.ok for r in res)
        # scrape-over-scrape: well-formed AND counters monotone
        assert T.lint_openmetrics(second, prev=first) == []
        samples = T.parse_openmetrics(second)
        tenants = {s["labels"].get("tenant") for s in samples
                   if s["name"] == "s2c_slo_phase_seconds"}
        assert tenants == {"ta", "tb"}
        phases = {s["labels"]["phase"] for s in samples
                  if s["name"] == "s2c_slo_phase_seconds"}
        assert phases == set(T.SLO_PHASES)
        folded = [s["value"] for s in samples
                  if s["name"] == "s2c_telemetry_jobs_folded_total"]
        assert folded == [4.0]
        # the live endpoint serves the same snapshot, fresh
        port = runner.http.port
        got = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).read() \
            .decode()
        assert T.lint_openmetrics(got, prev=second) == []
        hz = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10).read())
        assert hz["schema"] == "s2c-health/1"
        assert hz["jobs"]["run"] == 4
        code = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10).status
        assert code == 200
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"http://127.0.0.1:{port}/nope",
                                   timeout=10)
    finally:
        runner.close()
    # no objectives breached on a generous SLO
    assert runner.registry.value("slo/violations") == 0
    assert runner.admission.slo_burn_by_tenant == {}


# =========================================================================
# serve integration: manifest serve.slo + telemetry failure semantics
# =========================================================================
def test_manifest_carries_slo_verdict_and_burn(tmp_path):
    from sam2consensus_tpu.serve import JobSpec

    path = _sim(tmp_path, "m.sam", seed=77)
    mpath = str(tmp_path / "job.metrics")
    runner = _runner(slo="e2e=1ms")       # impossible: every job burns
    try:
        [r] = runner.submit_jobs([JobSpec(
            filename=path,
            config=RunConfig(**BASE, metrics_out=mpath),
            tenant="ta")])
    finally:
        runner.close()
    assert r.ok
    slo = r.manifest["serve"]["slo"]
    assert slo["tenant"] == "ta" and "e2e" in slo["violated"]
    assert slo["objectives_sec"] == {"e2e": 0.001}
    assert set(slo["phases_sec"]) == set(T.SLO_PHASES)
    assert slo["burn"]["e2e"] == 1
    # the on-disk manifest was rewritten with the verdict
    disk = json.load(open(mpath + ".manifest.json"))
    assert disk["serve"]["slo"]["violated"] == slo["violated"]
    assert runner.admission.slo_burn_by_tenant == {"ta": 1}
    # stats.extra surfaces the slo counters via the compat view
    assert r.metrics.get("slo/violations", 0) == 0  # job registry: none
    assert runner.registry.value("slo/violations/ta/e2e") == 1


def test_telemetry_write_failure_never_fails_job(tmp_path):
    from sam2consensus_tpu.serve import JobSpec

    path = _sim(tmp_path, "w.sam", seed=78)
    # a directory path makes every atomic replace fail
    bad = str(tmp_path / "isdir.prom")
    os.makedirs(bad)
    runner = _runner(telemetry_out=bad, telemetry_interval=0.0)
    try:
        [r] = runner.submit_jobs([JobSpec(filename=path,
                                          config=RunConfig(**BASE))])
    finally:
        runner.close()
    assert r.ok                                  # degraded, not dead
    assert runner.registry.value("telemetry/write_failed") > 0


# =========================================================================
# byte identity: telemetry on vs off
# =========================================================================
def test_byte_identity_telemetry_on_vs_off(tmp_path):
    from sam2consensus_tpu.serve import JobSpec

    paths = [_sim(tmp_path, f"b{k}.sam", seed=90 + k)
             for k in range(2)]

    def run(telemetry):
        kw = {}
        if telemetry:
            kw = dict(telemetry_out=str(tmp_path / "t.prom"),
                      telemetry_interval=0.05, slo="e2e=60s",
                      telemetry_port=0)
        runner = _runner(**kw)
        try:
            res = runner.submit_jobs(
                [JobSpec(filename=p, config=RunConfig(**BASE),
                         tenant="ta") for p in paths])
        finally:
            runner.close()
        assert all(r.ok for r in res)
        return [{n: render_file(rec, 0) for n, rec in r.fastas.items()}
                for r in res]

    assert run(False) == run(True)


# =========================================================================
# THE acceptance: journaled 8-job queue, one hung job
# =========================================================================
def test_hang_visible_mid_flight_with_slo_burn_and_capture(
        tmp_path, monkeypatch):
    from sam2consensus_tpu.serve import JobSpec

    monkeypatch.setenv("S2C_FAULT_HANG_S", "600")
    paths = [_sim(tmp_path, f"h{k}.sam", seed=300 + k)
             for k in range(8)]
    tele = str(tmp_path / "metrics.prom")
    health = str(tmp_path / "health.json")
    hang_job = 3                                  # tenant tb
    runner = _runner(journal_dir=str(tmp_path / "journal"),
                     stall_timeout=3.5,
                     telemetry_out=tele, health_out=health,
                     telemetry_interval=0.1, slo="e2e=2.5s")
    outdir = tmp_path / "out"
    outdir.mkdir()
    specs = []
    for k, p in enumerate(paths):
        # journal mode commits outputs to disk: outfolder must be the
        # test's tmp dir, not the pytest CWD
        cfg = RunConfig(**BASE, outfolder=str(outdir) + "/",
                        prefix=f"h{k}",
                        fault_inject="job_hang:timeout:0:1"
                        if k == hang_job else "")
        specs.append(JobSpec(filename=p, config=cfg,
                             job_id=f"h{k}",
                             tenant="ta" if k % 2 == 0 else "tb"))

    scrapes = []
    health_ages = []
    stop = threading.Event()

    def poller():
        prev = None
        armed = False
        while not stop.is_set():
            try:
                h = json.load(open(health))
            except (OSError, ValueError):
                time.sleep(0.03)
                continue
            if h.get("in_flight") == f"h{hang_job}":
                if not armed:
                    runner.profiler.request()     # SIGUSR2-equivalent
                    armed = True
                try:
                    text = open(tele).read()
                except OSError:
                    text = None
                if text and text != prev:
                    hb = None
                    for line in text.splitlines():
                        if line.startswith(
                                "s2c_serve_heartbeat_age_sec "):
                            hb = float(line.split()[-1])
                    scrapes.append(
                        (hb, T.lint_openmetrics(text, prev=prev)))
                    health_ages.append(
                        h.get("last_heartbeat_age_sec"))
                    prev = text
            time.sleep(0.06)

    t = threading.Thread(target=poller, daemon=True)
    t.start()
    try:
        res = runner.submit_jobs(specs)
    finally:
        stop.set()
        t.join(timeout=10)
        runner.close()

    # -- the hang cost exactly one job, the rest ran -------------------
    assert [r.ok for r in res] == [k != hang_job for k in range(8)]
    assert "HungDispatchError" in res[hang_job].error

    # -- exposition updated MID-HANG, heartbeat-aged, lint-clean -------
    ages = [hb for hb, _errs in scrapes if hb is not None]
    assert len(ages) >= 2, f"only {len(scrapes)} mid-hang scrapes"
    assert max(ages) > min(ages), "heartbeat age did not grow mid-hang"
    assert max(ages) > 1.0                       # visibly hung
    for _hb, errs in scrapes:
        assert errs == []                        # every scrape valid
    # the health file aged mid-hang too (stale-health-while-hung fix)
    hages = [a for a in health_ages if a is not None]
    assert hages and max(hages) > 1.0 and max(hages) > min(hages)

    # -- SLO burned exactly for the hung job's tenant ------------------
    assert runner.registry.value("slo/violations") == 1
    assert runner.registry.value("slo/violations/tb/e2e") == 1
    assert runner.admission.slo_burn_by_tenant == {"tb": 1}

    # -- per-tenant latency summaries present for BOTH tenants ---------
    final = open(tele).read()
    assert T.lint_openmetrics(final) == []
    samples = T.parse_openmetrics(final)

    def q(tenant, phase, quantile):
        for s in samples:
            if (s["name"] == "s2c_slo_phase_seconds"
                    and s["labels"].get("tenant") == tenant
                    and s["labels"].get("phase") == phase
                    and s["labels"].get("quantile") == quantile):
                return s["value"]
        return None

    for tenant in ("ta", "tb"):
        for phase in ("e2e", "queue_wait"):
            assert q(tenant, phase, "0.5") is not None
            assert q(tenant, phase, "0.99") is not None
    # the hung job dominates its tenant's p99 but not the other's
    assert q("tb", "e2e", "0.99") > 2.5
    assert q("ta", "e2e", "0.99") < 2.5
    # jobs behind the hang waited: queue_wait p99 reflects the stall
    assert q("ta", "queue_wait", "0.99") > 2.5

    # -- on-demand profiler capture produced DURING the hang -----------
    assert runner.profiler.captures == 1
    dump = os.path.join(runner.profiler.last_path, "span_dump.json")
    blob = json.load(open(dump))
    assert blob["context"]["in_flight"] == f"h{hang_job}"
    # the capture saw the wedged worker thread's stack
    assert any("serve-job" in name for name in blob["threads"])
    # it landed next to the journal
    assert runner.profiler.last_path.startswith(
        str(tmp_path / "journal"))
    assert runner.registry.value("telemetry/profile_captures") == 1
    assert runner.registry.value("telemetry/write_failed") == 0

    # -- health snapshot carries the slo + telemetry sections ----------
    h = json.load(open(health))
    assert h["slo"]["violations"] == 1
    assert h["slo"]["burn_by_tenant"] == {"tb": 1}
    assert h["telemetry"]["profile_captures"] == 1


# =========================================================================
# CLI surface
# =========================================================================
def test_serve_cli_telemetry_flags(tmp_path):
    from sam2consensus_tpu.cli import build_serve_parser, serve_main

    args = build_serve_parser().parse_args(
        ["-i", "x.sam", "--telemetry-out", "t.prom",
         "--telemetry-port", "0", "--slo", "e2e=5s,queue=1s",
         "--telemetry-interval", "0.5", "--log-format", "json",
         "--profile-capture-dir", "caps"])
    assert args.telemetry_out == "t.prom" and args.telemetry_port == 0
    assert args.slo == "e2e=5s,queue=1s"
    assert args.log_format == "json"
    # a typo'd objective fails the server start, loudly
    with pytest.raises(SystemExit):
        serve_main(["-i", "x.sam", "--slo", "nope=1s"])
    with pytest.raises(SystemExit):
        serve_main(["-i", "x.sam", "--slo", "e2e=fast"])


def test_one_shot_cli_log_format_flag():
    from sam2consensus_tpu.cli import build_parser, config_from_args

    args = build_parser().parse_args(
        ["-i", "x.sam", "--log-format", "json"])
    cfg = config_from_args(args)
    assert cfg.log_format == "json"
    assert config_from_args(build_parser().parse_args(
        ["-i", "x.sam"])).log_format == "text"
