"""IUPAC ambiguity table tests.

The expected mapping below is the fact table from the reference's ``amb``
dict (/root/reference/sam2consensus.py:317-329), spelled out entry by entry so
the derivation rule in ``constants.py`` is pinned against the original data.
"""

import numpy as np
import pytest

from sam2consensus_tpu.constants import (ALPHABET, AMB, BASE_TO_CODE,
                                         IUPAC_MASK_LUT, build_amb_table)

REFERENCE_AMB = {
    "-": "-", "A": "A", "C": "C", "G": "G", "N": "N", "T": "T",
    "-A": "a", "-C": "c", "-G": "g", "-N": "n", "-T": "t",
    "AC": "M", "AG": "R", "AN": "a", "AT": "W", "CG": "S",
    "CN": "c", "CT": "Y", "GN": "g", "GT": "K", "NT": "t",
    "-AC": "m", "-AG": "r", "-AN": "a", "-AT": "w", "-CG": "s",
    "-CN": "c", "-CT": "y", "-GN": "g", "-GT": "k", "-NT": "t",
    "ACG": "V", "ACN": "m", "ACT": "H", "AGN": "r", "AGT": "D",
    "ANT": "w", "CGN": "s", "CGT": "B", "CNT": "y", "GNT": "k",
    "-ACG": "v", "-ACN": "m", "-ACT": "h", "-AGN": "r", "-AGT": "d",
    "-ANT": "w", "-CGN": "s", "-CGT": "b", "-CNT": "y", "-GNT": "k",
    "ACGN": "v", "ACGT": "N", "ACNT": "h", "AGNT": "d", "CGNT": "b",
    "-ACGN": "v", "-ACGT": "N", "-ACNT": "h", "-AGNT": "d", "-CGNT": "b",
    "-ACGNT": "N",
}


def test_every_reference_entry_reproduced():
    for key, expected in REFERENCE_AMB.items():
        assert AMB[key] == expected, key


def test_reference_table_has_62_entries_we_cover_all_63():
    assert len(REFERENCE_AMB) == 62
    derived = build_amb_table()
    assert len(derived) == 63  # every non-empty subset of -ACGNT


def test_missing_reference_key_acgnt_fixed_to_N():
    # The reference forgot "ACGNT" (five-way tie, no gap) and would KeyError;
    # the framework defines it as "N" (documented fix, constants.py).
    assert "ACGNT" not in REFERENCE_AMB
    assert AMB["ACGNT"] == "N"


def test_mask_lut_agrees_with_amb():
    for mask in range(1, 64):
        key = "".join(sorted(ALPHABET[i] for i in range(6) if mask & (1 << i)))
        assert chr(IUPAC_MASK_LUT[mask]) == AMB[key], (mask, key)


def test_alphabet_is_ascii_sorted():
    assert list(ALPHABET) == sorted(ALPHABET)


def test_base_to_code_roundtrip():
    for i, ch in enumerate(ALPHABET):
        assert BASE_TO_CODE[ord(ch)] == i
    assert BASE_TO_CODE[ord("a")] == 255  # lowercase is out of contract (quirk 7)
    assert BASE_TO_CODE[ord("U")] == 255
