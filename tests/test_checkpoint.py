"""Checkpoint/resume, paranoid mode, and phase-timer observability.

SURVEY.md §5: the count tensor is the entire job state and is
sum-decomposable, so resume-after-crash must be exact — pinned here by
crashing a run mid-stream and comparing the resumed output byte-for-byte
against an uninterrupted run.
"""

import io

import numpy as np
import pytest

from sam2consensus_tpu.backends.cpu import CpuBackend
from sam2consensus_tpu.backends.jax_backend import JaxBackend
from sam2consensus_tpu.config import RunConfig
from sam2consensus_tpu.encoder.events import GenomeLayout, InsertionEvents
from sam2consensus_tpu.io.fasta import render_file
from sam2consensus_tpu.io.sam import ReadStream, read_header
from sam2consensus_tpu.utils import checkpoint as ckpt
from sam2consensus_tpu.utils.simulate import SimSpec, simulate


TEXT = simulate(SimSpec(n_contigs=4, contig_len=220, n_reads=600,
                        read_len=44, ins_read_rate=0.15, del_read_rate=0.15,
                        seed=17))


def _run(cfg, text=TEXT, handle_wrapper=None):
    handle = io.StringIO(text)
    contigs, _n, first = read_header(handle)
    if handle_wrapper is not None:
        handle = handle_wrapper(handle)
    stream = ReadStream(handle, first)
    backend = CpuBackend() if cfg.backend == "cpu" else JaxBackend()
    res = backend.run(contigs, stream, cfg)
    return ({n: render_file(r, 0) for n, r in res.fastas.items()},
            res.stats, stream)


class _CrashingHandle:
    """File-handle proxy that dies after ``limit`` lines (crash injection,
    SURVEY.md §5 failure detection)."""

    def __init__(self, handle, limit):
        self.handle = handle
        self.limit = limit
        self.count = 0

    def __iter__(self):
        for line in self.handle:
            self.count += 1
            if self.count > self.limit:
                raise RuntimeError("injected crash")
            yield line

    def read(self, n=-1):  # pragma: no cover - records() path only
        raise RuntimeError("injected crash")

    def readline(self):
        return self.handle.readline()

    def tell(self):
        return self.handle.tell()

    def seek(self, pos):
        return self.handle.seek(pos)


def test_roundtrip(tmp_path):
    ins = InsertionEvents()
    ins.contig_ids += [0, 1]
    ins.local_pos += [5, 7]
    ins.motifs += ["AC", "GGT"]
    counts = np.arange(60, dtype=np.int32).reshape(10, 6)
    ckpt.save(str(tmp_path), ckpt.CheckpointState(
        counts=counts, lines_consumed=123, reads_mapped=40, reads_skipped=2,
        aligned_bases=555, insertions=ins))
    state = ckpt.load(str(tmp_path), 10)
    np.testing.assert_array_equal(state.counts, counts)
    assert state.lines_consumed == 123
    assert state.reads_mapped == 40
    assert state.reads_skipped == 2
    assert state.aligned_bases == 555
    a = state.insertions.to_arrays()
    b = ins.to_arrays()
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_load_missing_returns_none(tmp_path):
    assert ckpt.load(str(tmp_path), 10) is None


def test_load_wrong_genome_raises(tmp_path):
    ckpt.save(str(tmp_path), ckpt.CheckpointState(
        counts=np.zeros((10, 6), np.int32), lines_consumed=0, reads_mapped=0,
        reads_skipped=0, aligned_bases=0, insertions=InsertionEvents()))
    with pytest.raises(ValueError):
        ckpt.load(str(tmp_path), 11)


# -- integrity digest (r6 satellite): corrupt == absent, never a crash ----
def _save_small(tmp_path, lines=9):
    ckpt.save(str(tmp_path), ckpt.CheckpointState(
        counts=np.arange(60, dtype=np.int32).reshape(10, 6),
        lines_consumed=lines, reads_mapped=4, reads_skipped=0,
        aligned_bases=55, insertions=InsertionEvents()))
    return ckpt.path_for(str(tmp_path))


def test_checkpoint_carries_crc32_digest(tmp_path):
    p = _save_small(tmp_path)
    with np.load(p) as z:
        assert "digest" in z.files
        assert z["digest"].dtype == np.uint32
    assert ckpt.load(str(tmp_path), 10) is not None


def test_truncated_checkpoint_loads_as_absent_with_counter(tmp_path):
    from sam2consensus_tpu.observability.metrics import pop_run, push_run

    p = _save_small(tmp_path)
    blob = open(p, "rb").read()
    with open(p, "wb") as fh:               # torn write / partial copy
        fh.write(blob[:len(blob) // 2])
    reg = push_run()
    try:
        assert ckpt.load(str(tmp_path), 10) is None
        assert reg.value("checkpoint/corrupt") == 1
    finally:
        pop_run(reg)


def test_digest_mismatch_loads_as_absent(tmp_path):
    from sam2consensus_tpu.observability.metrics import pop_run, push_run
    import zipfile

    p = _save_small(tmp_path)
    # bit-rot INSIDE the zip: rewrite the counts member with altered
    # bytes while keeping the npz structurally valid
    with np.load(p) as z:
        arrays = {k: z[k] for k in z.files}
    arrays["counts"] = arrays["counts"].copy()
    arrays["counts"][0, 0] += 1             # digest no longer matches
    with open(p, "wb") as fh:
        np.savez(fh, **arrays)
    with zipfile.ZipFile(p) as zf:          # still a readable npz
        assert "counts.npy" in zf.namelist()
    reg = push_run()
    try:
        assert ckpt.load(str(tmp_path), 10) is None
        assert reg.value("checkpoint/corrupt") == 1
    finally:
        pop_run(reg)


def test_pre_digest_checkpoint_still_loads(tmp_path):
    # a checkpoint written by an older writer (no digest entry) loads
    # undigested — upgrades must not invalidate in-flight resumes
    p = _save_small(tmp_path)
    with np.load(p) as z:
        arrays = {k: z[k] for k in z.files if k != "digest"}
    with open(p, "wb") as fh:
        np.savez(fh, **arrays)
    state = ckpt.load(str(tmp_path), 10)
    assert state is not None and state.lines_consumed == 9


def test_crash_resume_byte_identical(tmp_path):
    cfg = RunConfig(prefix="ck", thresholds=[0.25, 0.75], backend="jax",
                    decoder="py", chunk_reads=64,
                    checkpoint_dir=str(tmp_path), checkpoint_every=64)
    # phase 1: crash mid-stream, after at least one checkpoint
    with pytest.raises(RuntimeError, match="injected crash"):
        _run(cfg, handle_wrapper=lambda h: _CrashingHandle(h, 400))
    state = ckpt.load(str(tmp_path), GenomeLayout(
        read_header(io.StringIO(TEXT))[0]).total_len)
    assert state is not None and state.lines_consumed > 0

    # phase 2: resume on a fresh stream -> identical to an uninterrupted run
    out_resumed, stats, stream = _run(cfg)
    assert "resumed_from_line" in stats.extra
    # the checkpoint carried a byte offset, so the resume seeks in O(1)
    # instead of re-reading the consumed lines
    assert stats.extra["resume_mode"] == "seek"
    out_fresh, fresh_stats, _s = _run(
        RunConfig(prefix="ck", thresholds=[0.25, 0.75], backend="jax",
                  decoder="py", chunk_reads=64))
    assert out_resumed == out_fresh
    assert stats.reads_mapped == fresh_stats.reads_mapped
    assert stats.aligned_bases == fresh_stats.aligned_bases
    n_body_lines = sum(1 for l in TEXT.splitlines()
                       if l and not l.startswith("@"))
    assert stream.n_lines == n_body_lines
    # completed run removes its checkpoint
    assert ckpt.load(str(tmp_path), 880) is None


def test_resume_interops_with_native_decoder(tmp_path):
    """A checkpoint written by the python path resumes under the native
    decoder (and vice versa the state format is identical)."""
    from sam2consensus_tpu.encoder import native_encoder

    if not native_encoder.available():
        pytest.skip("C++ decoder unavailable")
    cfg_py = RunConfig(prefix="ck", thresholds=[0.25], backend="jax",
                       decoder="py", chunk_reads=64,
                       checkpoint_dir=str(tmp_path), checkpoint_every=64)
    with pytest.raises(RuntimeError, match="injected crash"):
        _run(cfg_py, handle_wrapper=lambda h: _CrashingHandle(h, 300))
    cfg_nat = RunConfig(prefix="ck", thresholds=[0.25], backend="jax",
                        decoder="native", checkpoint_dir=str(tmp_path))
    out_resumed, stats, _s = _run(cfg_nat)
    out_fresh, _st, _s2 = _run(RunConfig(prefix="ck", thresholds=[0.25],
                                         backend="jax", decoder="native"))
    assert out_resumed == out_fresh


def test_cpu_and_jax_agree_under_checkpointing(tmp_path):
    out_cpu, _st, _s = _run(RunConfig(prefix="ck", thresholds=[0.5]))
    out_jax, _st2, _s2 = _run(RunConfig(
        prefix="ck", thresholds=[0.5], backend="jax", decoder="py",
        chunk_reads=32, checkpoint_dir=str(tmp_path), checkpoint_every=32))
    assert out_jax == out_cpu


def test_paranoid_mode_clean_run():
    out_plain, _st, _s = _run(RunConfig(prefix="p", backend="jax",
                                        decoder="py"))
    out_paranoid, stats, _s2 = _run(RunConfig(prefix="p", backend="jax",
                                              decoder="py", paranoid=True))
    assert out_paranoid == out_plain
    assert stats.extra.get("paranoid_result_ok") is True
    assert stats.extra.get("paranoid_batches", 0) >= 1


def test_paranoid_catches_corrupt_batch():
    backend = JaxBackend()
    from sam2consensus_tpu.backends.base import BackendStats
    from sam2consensus_tpu.encoder.events import SegmentBatch

    bad = SegmentBatch(buckets={32: (np.array([10_000], dtype=np.int32),
                                     np.full((1, 32), 1, dtype=np.uint8))},
                       n_reads=1, n_events=32)
    with pytest.raises(RuntimeError, match="paranoid"):
        backend._paranoid_batch(bad, total_len=100, stats=BackendStats())


def test_phase_timers_reported():
    _out, stats, _s = _run(RunConfig(prefix="t", backend="jax",
                                     decoder="py"))
    for key in ("accumulate_sec", "vote_sec", "insertions_sec", "render_sec"):
        assert key in stats.extra


def test_incremental_two_shards_equal_one_run(tmp_path):
    """--incremental over two SAM shards == one run over the concatenation.

    SURVEY.md §5: the count tensor is sum-decomposable, so adding a new
    shard's counts on top of a checkpointed base and re-calling must be
    byte-identical to processing all reads at once.
    """
    import io

    from sam2consensus_tpu.backends.cpu import CpuBackend
    from sam2consensus_tpu.backends.jax_backend import JaxBackend
    from sam2consensus_tpu.io.fasta import render_file
    from sam2consensus_tpu.io.sam import ReadStream, read_header
    from sam2consensus_tpu.utils.simulate import SimSpec, simulate

    combined = simulate(SimSpec(n_contigs=3, contig_len=200, n_reads=550,
                                read_len=40, ins_read_rate=0.2, max_indel=3,
                                seed=71))
    lines = combined.splitlines(keepends=True)
    header = [ln for ln in lines if ln.startswith("@")]
    body = [ln for ln in lines if not ln.startswith("@")]
    text_a = "".join(header + body[:300])
    text_b = "".join(header + body[300:])

    def run(backend, text, cfg):
        handle = io.StringIO(text)
        contigs, _n, first = read_header(handle)
        res = backend.run(contigs, ReadStream(handle, first), cfg)
        return {n: render_file(r, 0) for n, r in res.fastas.items()}

    ck = str(tmp_path / "ck")
    cfg_a = RunConfig(prefix="p", thresholds=[0.25, 0.75],
                      checkpoint_dir=ck, incremental=True, source_id="a")
    cfg_b = RunConfig(prefix="p", thresholds=[0.25, 0.75],
                      checkpoint_dir=ck, incremental=True, source_id="b")
    run(JaxBackend(), text_a, cfg_a)            # shard 1: builds the base
    out_two = run(JaxBackend(), text_b, cfg_b)  # shard 2: adds on top

    out_one = run(CpuBackend(), combined,
                  RunConfig(prefix="p", thresholds=[0.25, 0.75]))
    assert out_two == out_one

    # idempotency: re-adding the SAME shard skips all its lines
    out_again = run(JaxBackend(), text_b, cfg_b)
    assert out_again == out_one


def test_incremental_rerun_of_older_shard_adds_nothing(tmp_path):
    """A, B, then A again: the non-latest shard is found in the
    checkpoint's absorbed-sources list and its reads are NOT re-added
    (the round-1 double-count hole)."""
    import io

    from sam2consensus_tpu.backends.cpu import CpuBackend
    from sam2consensus_tpu.backends.jax_backend import JaxBackend
    from sam2consensus_tpu.io.fasta import render_file
    from sam2consensus_tpu.io.sam import ReadStream, read_header
    from sam2consensus_tpu.utils.simulate import SimSpec, simulate

    combined = simulate(SimSpec(n_contigs=3, contig_len=180, n_reads=500,
                                read_len=40, ins_read_rate=0.2, max_indel=3,
                                seed=72))
    lines = combined.splitlines(keepends=True)
    header = [ln for ln in lines if ln.startswith("@")]
    body = [ln for ln in lines if not ln.startswith("@")]
    text_a = "".join(header + body[:250])
    text_b = "".join(header + body[250:])

    def run(backend, text, cfg):
        handle = io.StringIO(text)
        contigs, _n, first = read_header(handle)
        res = backend.run(contigs, ReadStream(handle, first), cfg)
        return ({n: render_file(r, 0) for n, r in res.fastas.items()},
                res.stats)

    ck = str(tmp_path / "ck")
    cfg_a = RunConfig(prefix="p", thresholds=[0.25, 0.75],
                      checkpoint_dir=ck, incremental=True, source_id="a")
    cfg_b = RunConfig(prefix="p", thresholds=[0.25, 0.75],
                      checkpoint_dir=ck, incremental=True, source_id="b")
    run(JaxBackend(), text_a, cfg_a)
    out_ab, _st = run(JaxBackend(), text_b, cfg_b)
    out_one, _st1 = run(CpuBackend(), combined,
                        RunConfig(prefix="p", thresholds=[0.25, 0.75]))
    assert out_ab == out_one

    out_dup, stats = run(JaxBackend(), text_a, cfg_a)  # A again, after B
    assert stats.extra.get("incremental_duplicate") == "a"
    assert out_dup == out_one

    # and the state on disk is still the clean A+B base afterwards
    out_b_again, _st2 = run(JaxBackend(), text_b, cfg_b)
    assert out_b_again == out_one


def test_incremental_rejects_stacking_on_crashed_shard(tmp_path):
    """A completes; B crashes mid-shard; adding C must be refused — the
    checkpoint holds B's untracked partial prefix, and stacking C on top
    would let a later rerun of B double-count that prefix."""
    ck = str(tmp_path / "ck")

    def cfg(src):
        return RunConfig(prefix="p", thresholds=[0.25], backend="jax",
                         decoder="py", chunk_reads=64, checkpoint_dir=ck,
                         checkpoint_every=64, incremental=True,
                         source_id=src)

    _out, _st, _s = _run(cfg("a"))                       # A completes
    with pytest.raises(RuntimeError, match="injected crash"):
        _run(cfg("b"), handle_wrapper=lambda h: _CrashingHandle(h, 400))
    with pytest.raises(RuntimeError, match="partially absorbed"):
        _run(cfg("c"))                                   # refuse stacking C
    with pytest.raises(RuntimeError, match="partially absorbed"):
        _run(cfg("a"))  # refuse even a no-op duplicate: its final write
        #               # would reset source/lines and launder B's prefix
    # finishing B unblocks: resume B, then C adds cleanly
    _out_b, st_b, _s2 = _run(cfg("b"))
    assert "resumed_from_line" in st_b.extra
    _out_c, st_c, _s3 = _run(cfg("c"))
    assert sorted(st_c.extra["incremental_base"]) == ["a", "b"]
