"""Memory observability plane (observability/memplane.py, ISSUE 14).

Covers the three tentpole surfaces — byte accounting (per-family
live/peak + the per-registry peak ratchet), the capacity ledger
decision, and OOM forensics (the ``mem_alloc`` fault site →
``mem_dump.json`` + CAPACITY classification + serve host-rung
demotion) — plus the neutrality contract: consensus bytes are
identical with the plane on or off, the PR 10/12 pattern.
"""

import json
import os
import types

import numpy as np
import pytest

from sam2consensus_tpu import observability as obs
from sam2consensus_tpu.observability import memplane
# the accessor function obs.metrics shadows the submodule name on the
# package — import the registry helpers from the module path directly
from sam2consensus_tpu.observability.metrics import pop_run, push_run


@pytest.fixture(autouse=True)
def _fresh_plane():
    memplane._reset_for_tests()
    yield
    memplane._reset_for_tests()


@pytest.fixture
def reg():
    r = push_run()
    yield r
    pop_run(r)


def _sim_sam(tmp_path, n_reads=1500, seed=7):
    from sam2consensus_tpu.utils.simulate import SimSpec, simulate

    path = tmp_path / "in.sam"
    path.write_text(simulate(SimSpec(
        n_contigs=2, contig_len=400, n_reads=n_reads, read_len=80,
        seed=seed)))
    return str(path)


def _run_backend(path, **cfg_kwargs):
    from sam2consensus_tpu.backends.jax_backend import JaxBackend
    from sam2consensus_tpu.config import RunConfig
    from sam2consensus_tpu.formats import open_alignment_input
    from sam2consensus_tpu.io.fasta import render_file

    cfg = RunConfig(prefix="mp", backend="jax", shards=1, **cfg_kwargs)
    ai = open_alignment_input(path, "auto", binary=True)
    try:
        res = JaxBackend().run(ai.contigs, ai.stream, cfg)
    finally:
        ai.close()
    rendered = {n: render_file(r, 0) for n, r in res.fastas.items()}
    return res, rendered


# =========================================================================
# Accounting choke point
# =========================================================================
class TestAccounting:
    def test_track_release_live_peak(self, reg):
        memplane.track("counts", 1000)
        memplane.track("counts", 500)
        memplane.release("counts", 500)
        s = memplane.summary()
        assert s["families"]["counts"]["live_bytes"] == 1000
        assert s["families"]["counts"]["peak_bytes"] == 1500
        # registry mirror: live gauge absolute, peak gauge ratcheted
        assert reg.value("mem/live_bytes/counts") == 1000
        assert reg.value("mem/peak_bytes/counts") == 1500
        assert reg.value("mem/peak_tracked_bytes") == 1500

    def test_peak_ratchet_is_concurrent_max_not_sum(self, reg):
        for _ in range(5):
            memplane.track("a", 100)
            memplane.release("a", 100)
        # five sequential 100-byte lives never coexisted: the ratchet
        # records the max concurrent footprint, not turnover
        assert reg.value("mem/peak_tracked_bytes") == 100

    def test_fresh_registry_sees_resident_carryover(self, reg):
        memplane.track("count_cache", 4096)       # resident before job
        r2 = push_run()
        try:
            memplane.track("counts", 100)
            # the new job's peak includes the resident cache entry
            assert r2.value("mem/peak_tracked_bytes") == 4196
        finally:
            pop_run(r2)

    def test_track_obj_releases_on_gc(self, reg):
        class Holder:
            pass

        h = Holder()
        memplane.track_obj("decode_ahead", h, 2048)
        assert memplane.summary()["families"]["decode_ahead"][
            "live_bytes"] == 2048
        del h
        import gc

        gc.collect()
        s = memplane.summary()["families"]["decode_ahead"]
        assert s["live_bytes"] == 0
        assert s["peak_bytes"] == 2048

    def test_disabled_plane_is_a_no_op(self, reg, monkeypatch):
        monkeypatch.setenv("S2C_MEMPLANE", "0")
        memplane.track("counts", 12345)
        assert memplane.summary()["tracked"]["live_bytes"] == 0
        assert reg.value("mem/peak_tracked_bytes") == 0

    def test_batch_nbytes(self):
        batch = types.SimpleNamespace(
            buckets={128: (np.zeros(4, np.int32),
                           np.zeros((4, 128), np.uint8))},
            staged={})
        assert memplane.batch_nbytes(batch) == 4 * 4 + 4 * 128


# =========================================================================
# Watermarks
# =========================================================================
class TestWatermarks:
    def test_sample_publishes_and_keeps_history(self, reg):
        s = memplane.sample()
        assert s["peak_rss_mb"] > 0
        assert reg.value("mem/peak_rss_mb") > 0
        for _ in range(3):
            memplane.sample()
        tail = memplane.history_tail(2)
        assert len(tail) == 2
        assert all("peak_rss_mb" in t for t in tail)

    def test_summary_shape(self, reg):
        memplane.track("counts", 10)
        s = memplane.summary()
        assert s["enabled"] is True
        assert s["tracked"]["live_bytes"] == 10
        assert "watermarks" in s


# =========================================================================
# Capacity model
# =========================================================================
class TestCapacity:
    def test_predict_monotonic(self):
        small, comp = memplane.predict_run_peak_bytes(10_000)
        big, _ = memplane.predict_run_peak_bytes(10_000_000)
        assert big > small
        assert set(comp) == {"counts_bytes", "staging_bytes",
                             "tail_bytes"}
        t1, _ = memplane.predict_run_peak_bytes(10_000, n_thresholds=1)
        t3, _ = memplane.predict_run_peak_bytes(10_000, n_thresholds=3)
        assert t3 > t1

    def test_record_capacity_joins_measured_ratchet(self):
        robs = obs.start_run()
        try:
            memplane.record_capacity(5000, n_thresholds=1,
                                     chunk_reads=2048)
            memplane.track("counts", 100_000)
            recs = obs.finalize_decisions()
        finally:
            obs.finish_run(robs)
        cap = next(r for r in recs if r.decision == "capacity")
        assert cap.predicted["bytes"] > 0
        assert cap.measured["bytes"] == 100_000
        assert "bytes" in cap.residual
        # informational residual (band=0): headroom never alarms
        assert cap.drift is False

    def test_budget_verdict(self):
        robs = obs.start_run()
        try:
            rec = memplane.record_capacity(5000, n_thresholds=1,
                                           chunk_reads=2048,
                                           budget_bytes=1)
        finally:
            obs.finish_run(robs)
        assert rec["chosen"] == "over_budget"


# =========================================================================
# OOM forensics
# =========================================================================
class TestForensics:
    def test_mem_dump_schema(self, tmp_path, reg):
        from sam2consensus_tpu.resilience.faultinject import \
            InjectedOomError

        memplane.track("counts", 777)
        memplane.record_capacity(1000, n_thresholds=1)
        exc = InjectedOomError("injected: RESOURCE_EXHAUSTED: oom")
        path = memplane.dump_on_capacity(exc, str(tmp_path),
                                         registry=reg,
                                         context={"job_id": "j1"})
        assert path is not None
        blob = json.loads(open(path).read())
        assert blob["schema"] == "s2c-mem-dump/1"
        assert blob["error"]["classification"] == "capacity"
        assert blob["families"]["counts"]["live_bytes"] == 777
        assert blob["capacity"]["predicted_bytes"] > 0
        assert blob["context"]["job_id"] == "j1"
        assert isinstance(blob["watermark_tail"], list)
        assert reg.value("mem/oom_dumps") == 1

    def test_non_capacity_errors_do_not_dump(self, tmp_path, reg):
        assert memplane.dump_on_capacity(
            ValueError("nope"), str(tmp_path), registry=reg) is None
        assert not (tmp_path / "mem_dump.json").exists()

    def test_injected_mem_alloc_writes_dump_next_to_metrics(
            self, tmp_path):
        path = _sim_sam(tmp_path)
        with pytest.raises(MemoryError):
            _run_backend(path, pileup="scatter",
                         fault_inject="mem_alloc:oom:0",
                         metrics_out=str(tmp_path / "m.jsonl"))
        from sam2consensus_tpu.resilience.policy import CAPACITY, classify
        from sam2consensus_tpu.resilience.faultinject import \
            InjectedOomError

        assert classify(InjectedOomError("x")) == CAPACITY
        dump = tmp_path / "mem_dump.json"
        assert dump.exists()
        blob = json.loads(dump.read_text())
        assert blob["error"]["classification"] == "capacity"
        assert blob["error"]["type"] == "InjectedOomError"

    def test_serve_oom_demotes_to_host_rung_with_forensics(
            self, tmp_path):
        """An injected allocation OOM in a serve job: the CAPACITY
        class must demote the job to the host rung (never blindly
        retry the same shape) AND leave mem_dump.json next to the
        journal."""
        from sam2consensus_tpu.config import RunConfig
        from sam2consensus_tpu.serve import JobSpec, ServeRunner

        path = _sim_sam(tmp_path)
        jdir = tmp_path / "journal"
        runner = ServeRunner(prewarm="off", decode_ahead=False,
                             persistent_cache=False,
                             journal_dir=str(jdir))
        try:
            cfg = RunConfig(backend="jax", prefix="mp", shards=1,
                            pileup="scatter",
                            on_device_error="fallback",
                            fault_inject="mem_alloc:oom:0",
                            outfolder=str(tmp_path / "out"))
            res = runner.submit_jobs([JobSpec(filename=path,
                                              config=cfg)])[0]
            assert res.ok, res.error
            assert res.rungs.get("pileup") == "host"   # demoted, not
            # blind-retried: the host rung allocates no device tensor
            assert runner.registry.value("serve/oom_dumps") == 1
            assert (jdir / "mem_dump.json").exists()
            snap = runner.health_snapshot()
            assert snap["memory"]["oom_dumps"] == 1
        finally:
            runner.close()


# =========================================================================
# Capacity-priced admission
# =========================================================================
class TestAdmission:
    def test_controller_capacity_reason(self):
        from sam2consensus_tpu.serve.admission import (
            REASON_CAPACITY, AdmissionController)

        adm = AdmissionController(mem_budget=100)
        adm.open_window()
        dec = adm.admit("t", predicted_bytes=1000)
        assert not dec.admitted and dec.reason == REASON_CAPACITY
        # unpriceable (header unreadable) jobs admit — the serial path
        # surfaces the real error
        assert adm.admit("t", predicted_bytes=None).admitted
        assert adm.admit("t", predicted_bytes=50).admitted

    def test_serve_sheds_over_budget_job(self, tmp_path):
        from sam2consensus_tpu.config import RunConfig
        from sam2consensus_tpu.serve import JobSpec, ServeRunner

        path = _sim_sam(tmp_path)
        runner = ServeRunner(prewarm="off", decode_ahead=False,
                             persistent_cache=False, mem_budget="64K")
        try:
            cfg = RunConfig(backend="jax", prefix="mp", shards=1,
                            outfolder=str(tmp_path / "out"))
            res = runner.submit_jobs([JobSpec(filename=path,
                                              config=cfg)])[0]
            assert not res.ok
            assert res.admission == "capacity"
            assert "mem-budget" in res.error
            assert runner.registry.value(
                "serve/admission_capacity") == 1
            snap = runner.health_snapshot()
            assert snap["admission"]["capacity"] == 1
            assert snap["memory"]["mem_budget_mb"] > 0
        finally:
            runner.close()

    def test_mem_budget_typo_fails_start(self):
        from sam2consensus_tpu.serve import ServeRunner

        with pytest.raises(ValueError, match="mem-budget"):
            ServeRunner(prewarm="off", persistent_cache=False,
                        mem_budget="lots")


# =========================================================================
# Neutrality + registry mirrors
# =========================================================================
class TestNeutralityAndSurfaces:
    @pytest.mark.parametrize("cfg_kwargs", [
        {"pileup": "scatter"},
        {"pileup": "host"},
        {"pileup": "scatter", "wire": "delta8"},
    ])
    def test_byte_identity_plane_on_vs_off(self, tmp_path, monkeypatch,
                                           cfg_kwargs):
        path = _sim_sam(tmp_path)
        monkeypatch.setenv("S2C_MEMPLANE", "1")
        _res_on, out_on = _run_backend(path, **cfg_kwargs)
        memplane._reset_for_tests()
        monkeypatch.setenv("S2C_MEMPLANE", "0")
        _res_off, out_off = _run_backend(path, **cfg_kwargs)
        assert out_on == out_off

    def test_h2d_mirrors_registry_choke_point(self, tmp_path):
        path = _sim_sam(tmp_path)
        res, _out = _run_backend(path, pileup="scatter")
        extra = res.stats.extra
        assert extra["h2d_bytes"] > 0
        # the compat key IS the registry counter now (satellite: h2d
        # billed through wire.account_h2d like d2h through account_d2h)
        assert extra["h2d_bytes"] == extra["wire/h2d_bytes"]
        # memory keys ride stats.extra + manifest
        assert extra["mem/peak_tracked_bytes"] > 0
        assert extra["peak_rss_mb"] > 0
        man = obs.last_manifest()
        assert man["memory"]["mem/peak_tracked_bytes"] > 0

    def test_openmetrics_mem_family(self, reg):
        from sam2consensus_tpu.observability.telemetry import (
            lint_openmetrics, render_openmetrics)

        memplane.track("counts", 4096)
        memplane.track("wire_staging", 1024)
        memplane.sample(reg)
        text = render_openmetrics(reg.snapshot())
        assert 's2c_mem_live_bytes{family="counts"} 4096' in text
        assert 's2c_mem_peak_bytes{family="wire_staging"} 1024' in text
        assert "# HELP s2c_mem_live_bytes " in text
        assert "s2c_mem_peak_rss_mb" in text
        assert lint_openmetrics(text) == []

    def test_s2c_top_memory_line(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "s2c_top", os.path.join(os.path.dirname(__file__), "..",
                                    "tools", "s2c_top.py"))
        s2c_top = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(s2c_top)
        health = {
            "uptime_sec": 10, "queue_depth": 0, "jobs": {},
            "admission": {"capacity": 2},
            "memory": {
                "families": {},
                "tracked": {"live_bytes": 5_000_000,
                            "peak_bytes": 9_000_000},
                "watermarks": {"rss_mb": 150.0, "peak_rss_mb": 200.0},
                "mem_budget_mb": 64.0,
                "oom_dumps": 1,
                "last_oom_dump": {"path": "/j/mem_dump.json"},
            },
        }
        lines = s2c_top.render(health, None)
        memline = next(ln for ln in lines if ln.startswith("memory:"))
        assert "5.0 MB live" in memline
        assert "9.0 MB peak" in memline
        assert "rss 150 MB" in memline
        assert "2 capacity-shed" in memline
        assert any("OOM forensics: 1 dump" in ln for ln in lines)


# =========================================================================
# Count-cache eviction visibility (satellite)
# =========================================================================
class TestCacheEviction:
    @staticmethod
    def _state(nbytes):
        counts = np.zeros(max(1, nbytes // 4), dtype=np.int32)
        return types.SimpleNamespace(
            counts=counts,
            insertions=types.SimpleNamespace(array_chunks=[]),
            sources=[])

    def test_eviction_emits_bytes(self, reg):
        from sam2consensus_tpu.serve.countcache import CountCache

        cache = CountCache(10_000)
        cache.put("a", self._state(6000), reg)
        cache.put("b", self._state(6000), reg)   # evicts a
        assert cache.evictions == 1
        assert reg.value("cache/evicted_bytes") >= 6000
        assert cache.stats()["evicted_mb"] > 0
        # memplane family mirrors cache residency
        fams = memplane.summary()["families"]
        assert fams["count_cache"]["live_bytes"] == cache.stats()[
            "resident_mb"] * 1e6
