"""Resilience subsystem: fault injection, retry/backoff, degradation ladder.

The failure contract pinned here (ISSUE 2 acceptance): with
``--fault-inject`` killing the device path mid-run — transient RPC
faults xN, then a persistent fault forcing a ladder demotion to host —
the run completes with FASTA bytes identical to the cpu oracle, the
metrics record the retries / demotion / emergency checkpoint, and a
kill+resume under injected faults recovers from the emergency
checkpoint.
"""

import io

import pytest

from sam2consensus_tpu import observability as obs
from sam2consensus_tpu.backends.cpu import CpuBackend
from sam2consensus_tpu.backends.jax_backend import JaxBackend
from sam2consensus_tpu.config import RunConfig
from sam2consensus_tpu.io.fasta import render_file
from sam2consensus_tpu.io.sam import ReadStream, read_header
from sam2consensus_tpu.resilience import faultinject, ladder, policy
from sam2consensus_tpu.utils.simulate import SimSpec, simulate

TEXT = simulate(SimSpec(n_contigs=3, contig_len=300, n_reads=900,
                        read_len=40, ins_read_rate=0.12, del_read_rate=0.12,
                        seed=5))


def _run(cfg, text=TEXT, handle_wrapper=None):
    handle = io.StringIO(text)
    contigs, _n, first = read_header(handle)
    if handle_wrapper is not None:
        handle = handle_wrapper(handle)
    stream = ReadStream(handle, first)
    backend = CpuBackend() if cfg.backend == "cpu" else JaxBackend()
    res = backend.run(contigs, stream, cfg)
    return ({n: render_file(r, 0) for n, r in res.fastas.items()},
            res.stats)


@pytest.fixture(scope="module")
def oracle():
    out, _ = _run(RunConfig(prefix="p", backend="cpu",
                            thresholds=[0.25, 0.75]))
    return out


def _jax_cfg(**kw):
    """A multi-batch device-pileup config: the python decoder honors
    chunk_reads (the native decoder batches by input block), and fast
    backoff keeps the suite quick."""
    base = dict(prefix="p", backend="jax", thresholds=[0.25, 0.75],
                decoder="py", pileup="scatter", chunk_reads=128,
                retry_backoff=0.001, shards=1)
    base.update(kw)
    return RunConfig(**base)


# ---------------------------------------------------------------- policy --
def test_classification():
    assert policy.classify(faultinject.InjectedRpcError("x")) \
        == policy.TRANSIENT
    assert policy.classify(TimeoutError("boom")) == policy.TRANSIENT
    assert policy.classify(ConnectionResetError("x")) == policy.TRANSIENT
    assert policy.classify(RuntimeError("UNAVAILABLE: socket closed")) \
        == policy.TRANSIENT
    assert policy.classify(RuntimeError(
        "RESOURCE_EXHAUSTED: out of memory")) == policy.CAPACITY
    assert policy.classify(MemoryError()) == policy.CAPACITY
    assert policy.classify(RuntimeError("INTERNAL: core dumped")) \
        == policy.FATAL
    # oracle-parity strict-mode error types can never be retried/demoted
    assert policy.classify(KeyError("'x'")) == policy.PASSTHROUGH
    assert policy.classify(ValueError("bad")) == policy.PASSTHROUGH
    assert policy.classify(KeyboardInterrupt()) == policy.PASSTHROUGH


def test_backoff_schedule_deterministic_and_exponential():
    a = policy.RetryPolicy(retries=5, backoff=0.1, jitter=0.1, seed=42)
    b = policy.RetryPolicy(retries=5, backoff=0.1, jitter=0.1, seed=42)
    da = [a.delay(i) for i in range(5)]
    db = [b.delay(i) for i in range(5)]
    assert da == db                       # seed-addressable jitter
    for i, d in enumerate(da):
        base = 0.1 * 2 ** i
        assert base * 0.9 <= d <= base * 1.1


def test_retry_run_retries_then_raises():
    pol = policy.RetryPolicy(retries=2, backoff=0.0)
    calls = []

    def flaky():
        calls.append(1)
        raise ConnectionError("UNAVAILABLE")

    with pytest.raises(policy.RetriesExhausted):
        pol.run(flaky, sleep=lambda s: None)
    assert len(calls) == 3                # 1 attempt + 2 retries

    calls.clear()

    def recovers():
        calls.append(1)
        if len(calls) < 3:
            raise TimeoutError("timed out")
        return "ok"

    assert pol.run(recovers, sleep=lambda s: None) == "ok"


def test_retry_never_touches_passthrough():
    pol = policy.RetryPolicy(retries=5, backoff=0.0)
    calls = []

    def bug():
        calls.append(1)
        raise KeyError("'N'")

    with pytest.raises(KeyError):
        pol.run(bug, sleep=lambda s: None)
    assert len(calls) == 1


def test_attempt_deadline():
    import time as _time

    pol = policy.RetryPolicy(retries=0, deadline_s=0.05)
    with pytest.raises(policy.AttemptDeadlineExceeded):
        pol._call(lambda: _time.sleep(1.0))
    # an overrun inside run() consumes the retry budget as a TRANSIENT
    assert policy.classify(policy.AttemptDeadlineExceeded("x")) \
        == policy.TRANSIENT
    with pytest.raises(policy.AttemptDeadlineExceeded):
        pol.run(lambda: _time.sleep(1.0))   # retries=0: original raises
    assert pol._call(lambda: 7) == 7      # under-deadline value passes


# ----------------------------------------------------------- faultinject --
def test_spec_parsing_and_errors():
    rules = faultinject.parse_spec(
        "pileup_dispatch:rpc:3:2, vote:fatal:0:inf")
    assert rules[0].site == "pileup_dispatch" and rules[0].after_n == 3 \
        and rules[0].times == 2
    assert rules[1].times == faultinject.PERSISTENT
    for bad in ("nosite:rpc:0", "vote:nokind:0", "vote:rpc:x",
                "vote:rpc", "vote:rpc:p2.0", "vote:rpc:0:0"):
        with pytest.raises(ValueError):
            faultinject.parse_spec(bad)


def test_counted_injection_and_suppression():
    inj = faultinject.FaultInjector(
        faultinject.parse_spec("vote:rpc:2:2"))
    inj.check("vote")                     # call 0: passes
    inj.check("vote")                     # call 1: passes
    for _ in range(2):                    # calls 2-3: fire
        with pytest.raises(faultinject.InjectedRpcError):
            inj.check("vote")
    inj.check("vote")                     # call 4: times exhausted
    assert inj.injected == {"vote": 2}

    inj2 = faultinject.FaultInjector(
        faultinject.parse_spec("vote:fatal:0:inf"))
    faultinject._injector = inj2
    try:
        with pytest.raises(faultinject.InjectedFatalError):
            faultinject.fault_check("vote")
        with faultinject.suppress():
            faultinject.fault_check("vote")   # suppressed: no raise
        with pytest.raises(faultinject.InjectedFatalError):
            faultinject.fault_check("vote")
    finally:
        faultinject._reset_for_tests()


def test_probabilistic_budget_honored():
    """An explicit times budget caps a probabilistic rule (p1.0 fires
    on every call until the budget runs out, then never again)."""
    inj = faultinject.FaultInjector(
        faultinject.parse_spec("vote:rpc:p1.0:2"), seed=1)
    fired = 0
    for _ in range(10):
        try:
            inj.check("vote")
        except faultinject.InjectedRpcError:
            fired += 1
    assert fired == 2
    # without a budget, probabilistic rules keep rolling
    assert faultinject.parse_spec("vote:rpc:p0.5")[0].times \
        == faultinject.PERSISTENT


def test_probabilistic_injection_seed_addressable():
    def fire_pattern(seed):
        inj = faultinject.FaultInjector(
            faultinject.parse_spec("vote:rpc:p0.3"), seed=seed)
        pat = []
        for _ in range(64):
            try:
                inj.check("vote")
                pat.append(0)
            except faultinject.InjectedRpcError:
                pat.append(1)
        return pat

    assert fire_pattern(7) == fire_pattern(7)      # deterministic
    assert fire_pattern(7) != fire_pattern(8)      # seed-addressable
    rate = sum(fire_pattern(7)) / 64
    assert 0.1 < rate < 0.6                        # roughly the asked p


# ---------------------------------------------------------------- ladder --
def test_split_batch_halves_rows():
    import numpy as np

    from sam2consensus_tpu.encoder.events import SegmentBatch

    starts = np.arange(32, dtype=np.int32)
    codes = np.zeros((32, 8), dtype=np.uint8)
    b = SegmentBatch(buckets={8: (starts, codes)}, n_reads=32,
                     n_events=256)
    halves = ladder.split_batch(b)
    assert len(halves) == 2
    got = np.concatenate([h.buckets[8][0] for h in halves])
    assert np.array_equal(np.sort(got), starts)
    # tiny buckets are not splittable
    tiny = SegmentBatch(buckets={8: (starts[:8], codes[:8])})
    assert ladder.split_batch(tiny) == [tiny]


def test_demote_pileup_rungs():
    from sam2consensus_tpu.ops.pileup import (HostPileupAccumulator,
                                              PileupAccumulator)

    acc = PileupAccumulator(64, strategy="auto")
    assert ladder.pileup_level(acc) == "device_auto"
    acc2, level = ladder.demote_pileup(acc, 64)
    assert acc2 is acc and level == "device_scatter"
    assert acc.strategy == "scatter" and acc._tuner is None
    acc3, level = ladder.demote_pileup(acc, 64)
    assert isinstance(acc3, HostPileupAccumulator) and level == "host"
    assert ladder.demote_pileup(acc3, 64) == (None, "")


# ------------------------------------------- end-to-end recovery (chaos) --
def test_transient_faults_retry_to_identical_output(oracle):
    """Transient RPC faults xN on the pileup dispatch: retried, then
    byte-identical output; retries recorded in the metrics."""
    got, stats = _run(_jax_cfg(
        on_device_error="retry",
        fault_inject="pileup_dispatch:rpc:1:2"))
    assert got == oracle
    assert stats.extra["fault/injected/pileup_dispatch"] == 2
    assert stats.extra["resilience/retries"] >= 2


def test_chaos_acceptance_metrics_jsonl(oracle, tmp_path):
    """THE acceptance scenario: transient RPC faults xN, then a
    persistent fatal fault forcing a ladder demotion to the host
    pileup — run completes, FASTA bytes identical to the cpu oracle,
    and the metrics JSONL records the retries, the demotion, and the
    emergency checkpoint write."""
    mpath = str(tmp_path / "metrics.jsonl")
    ckdir = str(tmp_path / "ck")
    got, stats = _run(_jax_cfg(
        on_device_error="fallback",
        checkpoint_dir=ckdir,
        metrics_out=mpath,
        fault_inject="pileup_dispatch:rpc:1:2,accumulate:fatal:4:inf"))
    assert got == oracle
    assert stats.extra["pileup_ladder"] == "host"
    counters = {}
    for row in obs.read_metrics_jsonl(mpath):
        if row.get("kind") == "counter":
            counters[row["name"]] = row["value"]
    assert counters["resilience/retries"] >= 2
    assert counters["resilience/demotions"] == 1
    assert counters["resilience/emergency_checkpoints"] == 1
    assert counters["fault/injected"] >= 3


def test_oom_splits_slab_and_completes(oracle):
    got, stats = _run(_jax_cfg(
        on_device_error="retry", chunk_reads=256,
        fault_inject="pileup_dispatch:oom:1:1"))
    assert got == oracle
    assert stats.extra["resilience/capacity_splits"] >= 1


def test_device_put_fault_recovers(oracle):
    got, stats = _run(_jax_cfg(
        on_device_error="retry",
        fault_inject="device_put:rpc:1:1"))
    assert got == oracle
    assert stats.extra["fault/injected/device_put"] == 1


def test_tail_transient_fault_recomputes(oracle):
    got, stats = _run(_jax_cfg(
        on_device_error="retry", fault_inject="vote:rpc:0:1"))
    assert got == oracle
    assert stats.extra["resilience/retries/tail"] == 1


def test_tail_persistent_fault_demotes_to_host(oracle):
    got, stats = _run(_jax_cfg(
        on_device_error="fallback", retries=1,
        fault_inject="vote:fatal:0:inf"))
    assert got == oracle
    assert stats.extra["resilience/demotions/tail"] == 1


def test_insertion_build_fault_recovers(oracle):
    got, stats = _run(_jax_cfg(
        on_device_error="retry",
        fault_inject="insertion_build:rpc:0:1"))
    assert got == oracle
    assert stats.extra["fault/injected/insertion_build"] == 1


def test_sharded_run_demotes_to_host(oracle):
    """A persistent device fault under --shards steps the sharded
    accumulator down to the host pileup; counts survive the demotion."""
    got, stats = _run(_jax_cfg(
        on_device_error="fallback", shards=2, shard_mode="dp",
        fault_inject="accumulate:fatal:3:inf"))
    assert got == oracle
    assert stats.extra["pileup_ladder"] == "host"
    assert stats.extra["resilience/demotions"] >= 1


def test_on_device_error_fail_raises():
    with pytest.raises(faultinject.InjectedRpcError):
        _run(_jax_cfg(on_device_error="fail",
                      fault_inject="pileup_dispatch:rpc:1:inf"))


def test_on_device_error_fail_raises_oom_without_splitting():
    """fail mode means 'raise immediately' for OOM too — no capacity
    splits, old-behavior parity."""
    with pytest.raises(faultinject.InjectedOomError):
        _run(_jax_cfg(on_device_error="fail",
                      fault_inject="pileup_dispatch:oom:1:inf"))


def test_retry_mode_does_not_demote():
    """Without fallback, a persistent fault stays fatal after retries."""
    with pytest.raises(faultinject.InjectedFatalError):
        _run(_jax_cfg(on_device_error="retry",
                      fault_inject="accumulate:fatal:2:inf"))


def test_multibucket_fault_retry_is_exact(tmp_path):
    """The retry/replay unit is the COMMIT unit (one width bucket): a
    transient fault on a batch's second bucket must not re-scatter its
    already-committed first bucket.  Mixed read spans force two width
    buckets per batch; serial decode (checkpoint on) keeps transfers on
    the per-bucket put path where the device_put site fires."""
    import random

    from sam2consensus_tpu.utils.simulate import sam_text

    rng = random.Random(0)
    rows = []
    for i in range(300):
        span = 20 if i % 2 == 0 else 70
        pos = rng.randrange(1, 400 - span)
        seq = "".join(rng.choice("ACGT") for _ in range(span))
        rows.append(("r", pos, f"{span}M", seq))
    text2 = sam_text([("r", 400)], rows)

    want, _ = _run(RunConfig(prefix="p", backend="cpu",
                             thresholds=[0.25, 0.75]), text=text2)
    got, stats = _run(_jax_cfg(
        on_device_error="retry", chunk_reads=64,
        checkpoint_dir=str(tmp_path / "ck"),
        fault_inject="device_put:rpc:1:1"), text=text2)
    assert got == want
    assert stats.extra["fault/injected/device_put"] == 1
    assert stats.extra["resilience/retries"] == 1


# -------------------------------------------------------- kill + resume --
class _CrashingHandle:
    """File-handle proxy that dies after ``limit`` lines (hard-crash
    injection on the DECODE side, which has no device ladder)."""

    def __init__(self, handle, limit):
        self.handle = handle
        self.limit = limit
        self.count = 0

    def __iter__(self):
        for line in self.handle:
            self.count += 1
            if self.count > self.limit:
                raise RuntimeError("injected hard crash")
            yield line

    def read(self, n=-1):  # pragma: no cover - records() path only
        raise RuntimeError("injected hard crash")

    def readline(self):
        line = self.handle.readline()
        if line:
            self.count += 1
            if self.count > self.limit:
                raise RuntimeError("injected hard crash")
        return line

    def tell(self):
        return self.handle.tell()

    def seek(self, pos):
        return self.handle.seek(pos)


def test_kill_after_demotion_resumes_from_emergency_checkpoint(
        oracle, tmp_path):
    """Demotion writes an emergency checkpoint; a hard crash AFTER the
    demotion (decode-side, past the ladder's reach) then resumes from
    that checkpoint and the resumed run's bytes match the oracle.
    checkpoint_every is huge, so the emergency write is the ONLY
    checkpoint the crashed run produced."""
    ckdir = str(tmp_path / "ck")
    from sam2consensus_tpu.utils import checkpoint as ckpt

    cfg = _jax_cfg(on_device_error="fallback", checkpoint_dir=ckdir,
                   checkpoint_every=10**9,
                   fault_inject="accumulate:fatal:2:inf")
    with pytest.raises(RuntimeError, match="injected hard crash"):
        _run(cfg, handle_wrapper=lambda h: _CrashingHandle(h, 700))
    contigs, _n, _first = read_header(io.StringIO(TEXT))
    total_len = sum(c.length for c in contigs)
    saved = ckpt.load(ckdir, total_len)
    assert saved is not None and saved.lines_consumed > 0

    cfg2 = _jax_cfg(on_device_error="retry", checkpoint_dir=ckdir)
    got, stats = _run(cfg2)
    assert got == oracle
    assert "resumed_from_line" in stats.extra


# ------------------------------------------------------- linkprobe stale --
def test_linkprobe_stale_fallback(monkeypatch):
    from sam2consensus_tpu.utils import linkprobe

    linkprobe._reset_for_tests()
    try:
        linkprobe._last_good = (0.01, 5e7)
        linkprobe._failed = True           # probe already failed once
        robs = obs.start_run()
        try:
            assert linkprobe.probe_link() == (0.01, 5e7)
            snap = obs.metrics().snapshot()
            assert snap["gauges"]["link/stale"]["value"] == 1.0
            assert snap["gauges"]["link/bps"]["value"] == 5e7
        finally:
            obs.finish_run(robs)
    finally:
        linkprobe._reset_for_tests()


def test_linkprobe_injected_fault_falls_back(monkeypatch):
    from sam2consensus_tpu.utils import linkprobe

    linkprobe._reset_for_tests()
    faultinject.configure("link_probe:rpc:0:inf")
    try:
        assert linkprobe.probe_link(force=True) is None
    finally:
        faultinject._reset_for_tests()
        linkprobe._reset_for_tests()


# ------------------------------------------------------------- cli flags --
def test_cli_fault_inject_spec_validated(tmp_path):
    from sam2consensus_tpu.cli import main
    from sam2consensus_tpu.utils.simulate import sam_text, write_sam

    sam = write_sam(sam_text([("r", 6)], [("r", 1, "4M", "ACGT")]),
                    str(tmp_path / "x.sam"))
    out = str(tmp_path / "out")
    with pytest.raises(SystemExit):
        main(["-i", sam, "-o", out, "--quiet", "--backend", "jax",
              "--fault-inject", "bogus:rpc:0"])
    # a valid spec that never fires runs clean end to end
    assert main(["-i", sam, "-o", out, "--quiet", "--backend", "jax",
                 "--fault-inject", "vote:rpc:999",
                 "--retries", "2", "--on-device-error", "fallback"]) == 0
