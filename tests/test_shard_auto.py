"""Model-driven --shard-mode auto + dynamic sp/dpsp halo (verdict r4 #3/#5).

Pins the decision table of ``parallel.auto.choose_shard_mode`` across the
(genome x depth x sortedness) axes, and the backend behavior the model
unlocks: auto-sp engaging for short-read inputs whose position blocks are
far below the old fixed 64 k halo, with the halo sized from the run's
observed widest row bucket (< 512 for a 150 bp-read fixture).
"""

import io

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from sam2consensus_tpu.parallel.auto import (  # noqa: E402
    choose_shard_mode, slab_stats)

MESH_1D = {"dp": 8, "sp": 1}
MESH_2D = {"dp": 2, "sp": 4}
TUNNEL = 40e6
PCIE = 2e9


# (name, L, rows, row_bytes, peak_frac, sorted_frac, halo, mesh, link)
DECISION_TABLE = [
    # small genome: dp's full-tensor reduce is cheap; routing never pays
    ("small_genome", 10_000, 250_000, 17_000_000, 0.15, 0.0,
     256, MESH_2D, TUNNEL, "dp"),
    ("small_genome_sorted", 10_000, 250_000, 17_000_000, 1.0, 1.0,
     256, MESH_2D, TUNNEL, "dp"),
    # huge genome, balanced unsorted reads: sp's halo-only overhead wins
    ("huge_unsorted", 250_000_000, 250_000, 17_000_000, 0.15, 0.0,
     256, MESH_1D, TUNNEL, "sp"),
    ("huge_unsorted_2d", 250_000_000, 250_000, 17_000_000, 0.15, 0.0,
     256, MESH_2D, TUNNEL, "sp"),
    # huge genome, coordinate-sorted: the window strategy absorbs the
    # slabs, so sp keeps winning at any imbalance
    ("huge_sorted", 250_000_000, 250_000, 17_000_000, 1.0, 1.0,
     256, MESH_2D, TUNNEL, "sp"),
    # huge genome + CLUSTERED-but-unsorted reads + slow link + 2-D mesh:
    # sp's slot grid would ship ~8x the rows over the tunnel; dpsp bounds
    # the inflation by n_sp and pays its macro-block reduce instead
    ("huge_clustered_tunnel", 250_000_000, 250_000, 17_000_000, 1.0, 0.0,
     256, MESH_2D, TUNNEL, "dpsp"),
    # same shape on a PCIe-class link: the inflated grid is cheap to
    # ship, so sp's smaller collective wins again
    ("huge_clustered_pcie", 250_000_000, 250_000, 17_000_000, 1.0, 0.0,
     256, MESH_2D, PCIE, "sp"),
    # mid-size genome where the old 2^25 rule said dp: the model routes
    # sp once the per-slab reduce outweighs the routing (verdict #3)
    ("mid_genome_shallow", 4_600_000, 20_000, 1_400_000, 0.15, 0.0,
     256, MESH_1D, TUNNEL, "sp"),
    # halo wider than the per-device block: sp/dpsp infeasible -> dp
    ("halo_exceeds_block", 100_000, 250_000, 17_000_000, 0.15, 0.0,
     65536, MESH_1D, TUNNEL, "dp"),
]


@pytest.mark.parametrize(
    "name,L,rows,rb,peak,sfrac,halo,mesh,link,want",
    DECISION_TABLE, ids=[row[0] for row in DECISION_TABLE])
def test_decision_table(name, L, rows, rb, peak, sfrac, halo, mesh, link,
                        want):
    n = mesh["dp"] * mesh["sp"]
    got = choose_shard_mode(L, n, mesh, rows, rb, peak, sfrac, halo, link)
    assert got == want, f"{name}: chose {got}, expected {want}"


def test_slab_stats_shapes():
    """Observed-slab statistics: balanced-random vs clustered slabs."""
    rng = np.random.default_rng(0)
    w = 256
    L = 1_000_000
    flat = rng.integers(0, L, 5000)
    codes = rng.integers(0, 6, (5000, w)).astype(np.uint8)
    rows, rb, mw, peak, sfrac = slab_stats({w: (flat, codes)}, L)
    assert rows == 5000 and mw == w
    assert rb == 5000 * (w // 2 + 4)
    assert peak < 0.1         # uniform spread: near-balanced
    assert sfrac == 0.0       # genome-wide span: window-ineligible
    clustered = rng.integers(0, 10_000, 5000) + 700_000
    rows, rb, mw, peak, sfrac = slab_stats({w: (clustered, codes)}, L)
    assert sfrac == 1.0       # tight span: window-absorbable
    # two distant clusters: window-ineligible AND imbalanced
    two = np.concatenate([rng.integers(0, 5_000, 4900),
                          rng.integers(995_000, 1_000_000, 100)])
    rows, rb, mw, peak, sfrac = slab_stats({w: (two, codes)}, L)
    assert peak > 0.9 and sfrac < 0.5


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs 8 (virtual) devices")
def test_auto_sp_engages_with_dynamic_halo(monkeypatch):
    """150 bp reads, 350 kbp genome, 8 shards: blocks ~44 k << 64 k.

    The old rule (sp only when total_len >= 2^25 AND block >= 65536)
    forced dp here at ANY link rate; the dynamic halo (observed widest
    bucket = 256) plus the cost model route it sp on a PCIe-class link,
    byte-identical to the oracle (verdict r4 #5's done criterion:
    halo < 512 on a 150 bp fixture).
    """
    from sam2consensus_tpu.backends.cpu import CpuBackend
    from sam2consensus_tpu.backends.jax_backend import JaxBackend
    from sam2consensus_tpu.config import RunConfig
    from sam2consensus_tpu.io.fasta import render_file
    from sam2consensus_tpu.io.sam import ReadStream, read_header
    from sam2consensus_tpu.utils.simulate import SimSpec, simulate

    monkeypatch.setenv("S2C_TAIL_LINK_MBPS", "2000")
    monkeypatch.setenv("S2C_LINK_PROBE", "0")
    text = simulate(SimSpec(n_contigs=1, contig_len=350_000,
                            n_reads=2_000, read_len=150,
                            contig_len_jitter=0.0, seed=9))

    def run(cfg):
        handle = io.StringIO(text) if cfg.backend == "cpu" \
            else io.BytesIO(text.encode())
        contigs, _n, first = read_header(handle)
        backend = CpuBackend() if cfg.backend == "cpu" else JaxBackend()
        res = backend.run(contigs, ReadStream(handle, first), cfg)
        return ({n: render_file(r, 0) for n, r in res.fastas.items()},
                res.stats)

    out_cpu, _ = run(RunConfig(prefix="h"))
    out_jax, stats = run(RunConfig(prefix="h", backend="jax", shards=8,
                                   shard_mode="auto"))
    assert out_jax == out_cpu
    assert stats.extra["shard_mode"] == "sp"
    assert stats.extra["halo"] < 512, stats.extra
    assert stats.extra["halo"] >= 256  # the 150 bp bucket (pow2 span)


def test_checkpoint_carries_max_row_width(tmp_path):
    """The observed widest bucket survives a checkpoint round trip."""
    from sam2consensus_tpu.encoder.events import InsertionEvents
    from sam2consensus_tpu.utils import checkpoint as ckpt

    state = ckpt.CheckpointState(
        counts=np.zeros((10, 6), np.int32), lines_consumed=1,
        reads_mapped=1, reads_skipped=0, aligned_bases=5,
        insertions=InsertionEvents(), byte_offset=100, max_row_width=512)
    ckpt.save(str(tmp_path), state)
    back = ckpt.load(str(tmp_path), 10)
    assert back.max_row_width == 512
