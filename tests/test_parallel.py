"""Sharded pipeline tests on the 8 virtual CPU devices (conftest).

SURVEY.md §4 "multi-device without a cluster": the same shard_map code later
runs unchanged on a real slice.  Exactness is guaranteed by sum-decomposition
of the count tensor; these tests pin it empirically.
"""

import io

import numpy as np
import pytest

import jax

from sam2consensus_tpu.backends.cpu import CpuBackend
from sam2consensus_tpu.backends.jax_backend import JaxBackend
from sam2consensus_tpu.config import RunConfig
from sam2consensus_tpu.encoder.events import GenomeLayout, ReadEncoder
from sam2consensus_tpu.io.fasta import render_file
from sam2consensus_tpu.io.sam import Contig, iter_records, read_header
from sam2consensus_tpu.ops.cutoff import encode_thresholds
from sam2consensus_tpu.ops.pileup import PileupAccumulator
from sam2consensus_tpu.parallel.dp import ShardedConsensus
from sam2consensus_tpu.parallel.mesh import factor_mesh, make_mesh
from sam2consensus_tpu.utils.simulate import SimSpec, sam_text, simulate


def test_factor_mesh():
    assert factor_mesh(8) == (4, 2)
    assert factor_mesh(7) == (7, 1)
    assert factor_mesh(4) == (2, 2)
    assert factor_mesh(1) == (1, 1)


def test_mesh_axes():
    mesh = make_mesh(8)
    assert mesh.axis_names == ("dp", "sp")
    assert mesh.size == 8


def _encode_all(text):
    handle = io.StringIO(text)
    contigs, _n, first = read_header(handle)
    layout = GenomeLayout(contigs)
    enc = ReadEncoder(layout)
    chunks = list(enc.encode_segments(iter_records(handle, first),
                                      chunk_reads=64))
    return layout, chunks


def test_sharded_counts_equal_single_device():
    text = simulate(SimSpec(n_contigs=4, contig_len=200, n_reads=500,
                            read_len=50, seed=21))
    layout, chunks = _encode_all(text)

    single = PileupAccumulator(layout.total_len)
    for c in chunks:
        single.add(c)
    expected = np.asarray(single.counts)

    sharded = ShardedConsensus(make_mesh(8), layout.total_len)
    for c in chunks:
        sharded.add(c)
    np.testing.assert_array_equal(sharded.counts_host(), expected)


def test_sharded_vote_equals_single_vote():
    text = simulate(SimSpec(n_contigs=3, contig_len=150, n_reads=400,
                            read_len=40, seed=22))
    layout, chunks = _encode_all(text)
    sharded = ShardedConsensus(make_mesh(8), layout.total_len)
    for c in chunks:
        sharded.add(c)
    thr_enc = encode_thresholds([0.25, 0.75])
    syms = sharded.vote(thr_enc, min_depth=1)

    from sam2consensus_tpu.ops.vote import vote_positions
    import jax.numpy as jnp
    syms1, cov1 = vote_positions(jnp.asarray(sharded.counts_host()),
                                 jnp.asarray(thr_enc), 1)
    np.testing.assert_array_equal(syms, np.asarray(syms1))

    # device-side tail stats == host recomputation (contig sums + site cov)
    cov_host = np.asarray(cov1, dtype=np.int64)
    site_keys = np.asarray([0, 5, layout.total_len - 1, -1], dtype=np.int32)
    contig_sums, site_cov = sharded.tail_stats(
        layout.offsets.astype(np.int32), site_keys)
    want = [cov_host[int(layout.offsets[i]):int(layout.offsets[i + 1])].sum()
            for i in range(len(layout.names))]
    np.testing.assert_array_equal(contig_sums, want)
    np.testing.assert_array_equal(
        site_cov, [cov_host[0], cov_host[5], cov_host[-1], 0])


def test_sharded_auto_autotunes_and_stays_exact():
    """--shards + --pileup auto runs the measured scatter-vs-mxu trial
    (the same PileupAutoTuner as single-device) and locks a winner, with
    every trial slab still accumulating exactly (VERDICT r2 #3)."""
    from sam2consensus_tpu.encoder.events import SegmentBatch

    rng = np.random.default_rng(58)
    total_len = 16000
    width = 32
    rows = 1 << 15                 # x32 = 1M cells: enters the trial
    auto = ShardedConsensus(make_mesh(8), total_len, pileup="auto")
    plain = ShardedConsensus(make_mesh(8), total_len, pileup="scatter")
    for _ in range(6):
        starts = rng.integers(0, total_len - width, rows).astype(np.int32)
        codes = rng.integers(0, 6, (rows, width)).astype(np.uint8)
        batch = SegmentBatch(buckets={width: (starts, codes)},
                             n_reads=rows, n_events=rows * width)
        auto.add(batch)
        plain.add(batch)
    tune = auto.strategy_used.get("autotune")
    assert tune is not None and tune["winner"] in ("scatter", "mxu"), \
        auto.strategy_used
    assert tune["scatter_sec_per_mcell"] > 0
    assert tune["mxu_sec_per_mcell"] > 0
    np.testing.assert_array_equal(auto.counts_host(), plain.counts_host())


def test_restore_roundtrip():
    layout = GenomeLayout([Contig("a", 40), Contig("b", 25)])
    sharded = ShardedConsensus(make_mesh(8), layout.total_len)
    rng = np.random.RandomState(0)
    counts = rng.randint(0, 50, size=(layout.total_len, 6)).astype(np.int32)
    sharded.restore(counts)
    np.testing.assert_array_equal(sharded.counts_host(), counts)


@pytest.mark.parametrize("shards", [2, 8])
def test_sharded_backend_byte_identical(shards):
    text = simulate(SimSpec(n_contigs=5, contig_len=180, n_reads=600,
                            read_len=40, ins_read_rate=0.15,
                            del_read_rate=0.15, seed=23))

    def run(cfg):
        handle = io.StringIO(text)
        contigs, _n, first = read_header(handle)
        res = (CpuBackend() if cfg.backend == "cpu" else JaxBackend()).run(
            contigs, iter_records(handle, first), cfg)
        return {n: render_file(r, 0) for n, r in res.fastas.items()}

    cfg_cpu = RunConfig(prefix="p", thresholds=[0.25, 0.75], backend="cpu")
    cfg_jax = RunConfig(prefix="p", thresholds=[0.25, 0.75], backend="jax",
                        shards=shards)
    assert run(cfg_jax) == run(cfg_cpu)


def test_shards_exceeding_devices_raises():
    with pytest.raises(ValueError):
        make_mesh(99)


def test_sharded_six_devices():
    # non-power-of-two device count: power-of-two row batches must still
    # shard evenly (exercises the row-padding-to-multiple-of-n path)
    text = simulate(SimSpec(n_contigs=2, contig_len=120, n_reads=300,
                            read_len=40, seed=31))
    layout, chunks = _encode_all(text)
    single = PileupAccumulator(layout.total_len)
    sharded = ShardedConsensus(make_mesh(6), layout.total_len)
    for c in chunks:
        single.add(c)
        sharded.add(c)
    np.testing.assert_array_equal(sharded.counts_host(),
                                  np.asarray(single.counts))


def test_sharded_mxu_counts_equal_scatter():
    """dp + per-device MXU pileup == dp + scatter (task: fast kernels
    compose with --shards)."""
    text = simulate(SimSpec(n_contigs=3, contig_len=220, n_reads=500,
                            read_len=40, seed=41))
    layout, chunks = _encode_all(text)
    scatter = ShardedConsensus(make_mesh(8), layout.total_len,
                               pileup="scatter")
    mxu = ShardedConsensus(make_mesh(8), layout.total_len, pileup="mxu")
    for c in chunks:
        scatter.add(c)
        mxu.add(c)
    assert any(k.startswith("mxu") for k in mxu.strategy_used), \
        mxu.strategy_used
    np.testing.assert_array_equal(mxu.counts_host(), scatter.counts_host())


@pytest.mark.parametrize("kernels", [
    {"pileup": "mxu"},
    {"ins_kernel": "pallas"},
    {"pileup": "mxu", "ins_kernel": "pallas"},
])
def test_sharded_backend_with_fast_kernels_byte_identical(kernels):
    """--shards composed with --pileup mxu / --insertion-kernel pallas."""
    text = simulate(SimSpec(n_contigs=4, contig_len=200, n_reads=600,
                            read_len=40, ins_read_rate=0.2,
                            del_read_rate=0.15, seed=42))

    def run(cfg):
        handle = io.StringIO(text)
        contigs, _n, first = read_header(handle)
        res = (CpuBackend() if cfg.backend == "cpu" else JaxBackend()).run(
            contigs, iter_records(handle, first), cfg)
        return ({n: render_file(r, 0) for n, r in res.fastas.items()},
                res.stats)

    out_cpu, _st = run(RunConfig(prefix="p", thresholds=[0.25, 0.75]))
    out_jax, stats = run(RunConfig(prefix="p", thresholds=[0.25, 0.75],
                                   backend="jax", shards=8, **kernels))
    assert out_jax == out_cpu
    if kernels.get("pileup") == "mxu":
        assert any(k.startswith("mxu") for k in stats.extra["pileup"])
    if kernels.get("ins_kernel") == "pallas":
        assert stats.extra.get("insertion_kernel") == "pallas"


@pytest.mark.parametrize("mode,pileup", [
    ("sp", "mxu"), ("sp", "pallas"),
    ("dpsp", "mxu"), ("dpsp", "pallas"),
])
def test_sp_modes_compose_with_device_kernels(mode, pileup):
    """--pileup mxu|pallas with --shard-mode sp|dpsp is byte-identical
    (round-4 verdict #4: the position routers feed the kernel planners
    directly; the old RuntimeError is gone)."""
    # sparse coverage: the slab's position span fails the window
    # strategy's density gate, so the ROUTED path (the kernel one) runs
    text = simulate(SimSpec(n_contigs=1, contig_len=40_000, n_reads=200,
                            read_len=30, ins_read_rate=0.15,
                            del_read_rate=0.1, seed=43))

    def run(cfg):
        handle = io.StringIO(text)
        contigs, _n, first = read_header(handle)
        res = (CpuBackend() if cfg.backend == "cpu" else JaxBackend()).run(
            contigs, iter_records(handle, first), cfg)
        return ({n: render_file(r, 0) for n, r in res.fastas.items()},
                res.stats)

    out_cpu, _st = run(RunConfig(prefix="p"))
    out_jax, stats = run(RunConfig(prefix="p", backend="jax", shards=8,
                                   shard_mode=mode, pileup=pileup))
    assert out_jax == out_cpu
    assert stats.extra["shard_mode"] == mode
    # the sparse fixture must actually exercise the routed kernel (a
    # window_ key here would mean the density gate swallowed the slab)
    prefix = ("routed_" if mode == "sp" else "dpsp_") + pileup
    assert any(k.startswith(prefix)
               for k in stats.extra["pileup"]), stats.extra["pileup"]
