"""Self-calibrating link constants for the tail-placement model.

The placement gates must route correctly on an un-tuned host with NO env
vars: the startup probe (utils/linkprobe) feeds measured link constants
to ``_link_constants``, and a PCIe-class link vs the tunneled-chip link
flip ``_tail_cpu_wins`` for the same tail (round-3 verdict item 4).
"""

import jax
import pytest

from sam2consensus_tpu.backends import jax_backend as jb
from sam2consensus_tpu.utils import linkprobe


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    for var in ("S2C_TAIL_RT_MS", "S2C_TAIL_LINK_MBPS", "S2C_LINK_PROBE",
                "S2C_TAIL_DEVICE"):
        monkeypatch.delenv(var, raising=False)
    linkprobe._reset_for_tests()
    yield
    linkprobe._reset_for_tests()


def test_probe_feeds_constants_and_flips_routing(monkeypatch):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    # PCIe-class link (sub-ms RT, ~10 GB/s): the chip wins a 1M-position
    # native tail (cpu cost ~31 ms vs ~1 ms of link)
    monkeypatch.setattr(linkprobe, "probe_link",
                        lambda force=False: (5e-4, 10e9))
    assert jb._link_constants() == (5e-4, 10e9)
    assert not jb._tail_cpu_wins(1_000_000, 1, 6_000_000, True)
    # tunneled-chip link (65 ms RT, 40 MB/s): the same tail routes cpu
    monkeypatch.setattr(linkprobe, "probe_link",
                        lambda force=False: (65e-3, 40e6))
    assert jb._link_constants() == (65e-3, 40e6)
    assert jb._tail_cpu_wins(1_000_000, 1, 6_000_000, True)


def test_env_overrides_beat_probe(monkeypatch):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(
        linkprobe, "probe_link",
        lambda force=False: pytest.fail("probe must not run with env set"))
    monkeypatch.setenv("S2C_TAIL_RT_MS", "100")
    monkeypatch.setenv("S2C_TAIL_LINK_MBPS", "1")
    assert jb._link_constants() == (0.1, 1e6)


def test_probe_disabled_uses_defaults(monkeypatch):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setenv("S2C_LINK_PROBE", "0")
    monkeypatch.setattr(
        linkprobe, "probe_link",
        lambda force=False: pytest.fail("probe disabled"))
    assert jb._link_constants() == (jb.TAIL_RT_SEC_DEFAULT,
                                    jb.TAIL_LINK_BPS_DEFAULT)


def test_cpu_backend_skips_probe(monkeypatch):
    # tests run on the XLA CPU backend: link-free, probe never consulted
    monkeypatch.setattr(
        linkprobe, "probe_link",
        lambda force=False: pytest.fail("cpu backend must not probe"))
    assert jb._link_constants() == (jb.TAIL_RT_SEC_DEFAULT,
                                    jb.TAIL_LINK_BPS_DEFAULT)


def test_probe_failure_falls_back(monkeypatch):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(linkprobe, "probe_link", lambda force=False: None)
    assert jb._link_constants() == (jb.TAIL_RT_SEC_DEFAULT,
                                    jb.TAIL_LINK_BPS_DEFAULT)


def test_probe_watchdog_times_out_hung_device(monkeypatch):
    """A transport that died after backend init blocks forever inside the
    probe's device calls; the watchdog deadline must turn that into a
    remembered failure (gates fall back to defaults) instead of a hang."""
    import time as _time

    monkeypatch.setenv("S2C_LINK_PROBE_TIMEOUT_S", "0.2")
    monkeypatch.setattr(linkprobe, "_probe_into",
                        lambda box: _time.sleep(30))
    t0 = _time.perf_counter()
    assert linkprobe.probe_link(force=True) is None
    assert _time.perf_counter() - t0 < 5
    # failure is remembered: no second hang
    assert linkprobe.probe_link() is None


def test_real_probe_on_cpu_device_measures_sane_numbers():
    # the probe itself (against the test CPU backend, forced): returns
    # clamped, positive numbers and caches
    out = linkprobe.probe_link(force=True)
    assert out is not None
    rt, bw = out
    assert 1e-6 <= rt <= 10.0
    assert 1e5 <= bw <= 1e12
    assert linkprobe.probe_link() == out    # cached


def test_cache_entries_stamped_with_measured_at(tmp_path, monkeypatch):
    """A successful probe writes a timestamped cache entry; a later
    process reads it back with its age."""
    import json
    import time

    cache = tmp_path / "link.json"
    monkeypatch.setenv("S2C_LINK_CACHE", str(cache))
    monkeypatch.setattr(linkprobe, "_probe_into",
                        lambda box: box.append((0.01, 5e7)))
    assert linkprobe.probe_link(force=True) == (0.01, 5e7)
    blob = json.loads(cache.read_text())
    assert abs(blob["measured_at"] - time.time()) < 60
    info = linkprobe.link_info()
    assert info["source"] == "probed"
    assert info["age_sec"] < 60


def test_stale_cache_older_than_max_age_warns(tmp_path, monkeypatch,
                                              caplog):
    """Constants older than S2C_LINK_CACHE_MAX_AGE still serve (better
    than another rig's baked defaults) but emit link/stale_age + a
    warning instead of silently pricing from drifted numbers."""
    import json
    import logging
    import time

    from sam2consensus_tpu import observability as obs

    cache = tmp_path / "link.json"
    cache.write_text(json.dumps(
        {"rt_sec": 0.07, "bps": 12e6,
         "measured_at": time.time() - 10 * 86400}))    # 10 days old
    monkeypatch.setenv("S2C_LINK_CACHE", str(cache))
    monkeypatch.setattr(linkprobe, "_probe_into",
                        lambda box: box.append(None))  # probe fails
    robs = obs.start_run()
    try:
        with caplog.at_level(logging.WARNING,
                             "sam2consensus_tpu.utils.linkprobe"):
            assert linkprobe.probe_link(force=True) == (0.07, 12e6)
        snap = robs.registry.snapshot()
        assert snap["gauges"]["link/stale"]["value"] == 1.0
        age = snap["gauges"]["link/stale_age"]["value"]
        assert 9 * 86400 < age < 11 * 86400
        assert any("placement model is pricing" in r.message
                   for r in caplog.records)
        assert linkprobe.link_info()["source"] == "stale-cache"
    finally:
        obs.finish_run(robs)


def test_fresh_stale_cache_serves_quietly(tmp_path, monkeypatch, caplog):
    """A recent cache entry (within max age) serves without the age
    alarm — link/stale still marks it as memory, not measurement."""
    import json
    import logging
    import time

    from sam2consensus_tpu import observability as obs

    cache = tmp_path / "link.json"
    cache.write_text(json.dumps(
        {"rt_sec": 0.07, "bps": 12e6, "measured_at": time.time() - 60}))
    monkeypatch.setenv("S2C_LINK_CACHE", str(cache))
    monkeypatch.setattr(linkprobe, "_probe_into",
                        lambda box: box.append(None))
    robs = obs.start_run()
    try:
        with caplog.at_level(logging.WARNING,
                             "sam2consensus_tpu.utils.linkprobe"):
            assert linkprobe.probe_link(force=True) == (0.07, 12e6)
        snap = robs.registry.snapshot()
        assert snap["gauges"]["link/stale"]["value"] == 1.0
        assert "link/stale_age" not in snap["gauges"]
        assert not caplog.records
    finally:
        obs.finish_run(robs)


def test_legacy_cache_without_timestamp_treated_stale(tmp_path,
                                                      monkeypatch):
    """Pre-timestamp cache entries have unknown age: flagged (-1) rather
    than trusted silently."""
    import json

    from sam2consensus_tpu import observability as obs

    cache = tmp_path / "link.json"
    cache.write_text(json.dumps({"rt_sec": 0.05, "bps": 30e6}))
    monkeypatch.setenv("S2C_LINK_CACHE", str(cache))
    monkeypatch.setattr(linkprobe, "_probe_into",
                        lambda box: box.append(None))
    robs = obs.start_run()
    try:
        assert linkprobe.probe_link(force=True) == (0.05, 30e6)
        snap = robs.registry.snapshot()
        assert snap["gauges"]["link/stale_age"]["value"] == -1.0
    finally:
        obs.finish_run(robs)


def test_link_cache_max_age_env_override(monkeypatch):
    monkeypatch.setenv("S2C_LINK_CACHE_MAX_AGE", "3600")
    assert linkprobe.cache_max_age() == 3600.0
    monkeypatch.setenv("S2C_LINK_CACHE_MAX_AGE", "junk")
    assert linkprobe.cache_max_age() == linkprobe.CACHE_MAX_AGE_SEC


# -- atomic cache write + corrupt tolerance (r6 satellite) ---------------
def test_cache_write_is_atomic(tmp_path, monkeypatch):
    """The cache lands via tmp + os.replace — no window where the file
    exists truncated (pinned by patching os.replace to observe the
    temp file's complete content before the swap)."""
    import json
    import os as _os

    cache = tmp_path / "link.json"
    monkeypatch.setenv("S2C_LINK_CACHE", str(cache))
    seen = {}
    real_replace = _os.replace

    def spy(src, dst):
        seen["tmp_content"] = open(src).read()
        seen["dst_existed"] = _os.path.exists(dst)
        return real_replace(src, dst)

    monkeypatch.setattr(linkprobe.os, "replace", spy)
    linkprobe._write_cache((0.02, 3e7))
    assert json.loads(seen["tmp_content"])["bps"] == 3e7   # complete
    assert json.loads(cache.read_text())["rt_sec"] == 0.02
    assert not list(tmp_path.glob("*.tmp"))                # no droppings


def test_corrupt_cache_tolerated_with_warning(tmp_path, monkeypatch,
                                              caplog):
    """A truncated/corrupt cache file reads as absent — the probe runs
    instead of the process crashing — and flags link/cache_corrupt."""
    import logging

    from sam2consensus_tpu import observability as obs

    cache = tmp_path / "link.json"
    cache.write_text('{"rt_sec": 0.01, "bps": 4e7, "measu')   # torn
    monkeypatch.setenv("S2C_LINK_CACHE", str(cache))
    monkeypatch.setattr(linkprobe, "_probe_into",
                        lambda box: box.append(None))   # probe fails too
    robs = obs.start_run()
    try:
        with caplog.at_level(logging.WARNING,
                             logger="sam2consensus_tpu.utils.linkprobe"):
            # probe fails, stale fallback consults the (corrupt) cache:
            # both degrade cleanly to None -> baked defaults
            assert linkprobe.probe_link(force=True) is None
        snap = robs.registry.snapshot()
        assert snap["gauges"]["link/cache_corrupt"]["value"] == 1.0
        assert any("corrupt" in r.message for r in caplog.records)
    finally:
        obs.finish_run(robs)


def test_corrupt_cache_does_not_block_fresh_probe(tmp_path, monkeypatch):
    """With a corrupt cache on disk, a SUCCESSFUL probe still serves
    measured constants and atomically repairs the cache file."""
    import json

    cache = tmp_path / "link.json"
    cache.write_text("not json at all")
    monkeypatch.setenv("S2C_LINK_CACHE", str(cache))
    monkeypatch.setattr(linkprobe, "_probe_into",
                        lambda box: box.append((0.015, 6e7)))
    assert linkprobe.probe_link(force=True) == (0.015, 6e7)
    assert json.loads(cache.read_text())["bps"] == 6e7     # repaired
