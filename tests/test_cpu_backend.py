"""CPU golden backend tests: hand-computed expected outputs for every quirk.

Each test pins a behavior documented in SURVEY.md §2 against expectations
worked out by hand from the spec (/root/reference/sam2consensus.py).
"""

import io

import pytest

from sam2consensus_tpu.backends.cpu import CpuBackend
from sam2consensus_tpu.config import RunConfig
from sam2consensus_tpu.io.sam import read_header, iter_records
from sam2consensus_tpu.utils.simulate import sam_text


def run_cpu(text, **cfg_kwargs):
    cfg = RunConfig(prefix="p", **cfg_kwargs)
    handle = io.StringIO(text)
    contigs, _n, first = read_header(handle)
    return CpuBackend().run(contigs, iter_records(handle, first), cfg)


def test_basic_consensus_and_header():
    text = sam_text([("ref1", 10)], [
        ("ref1", 1, "4M", "ACGT"),
        ("ref1", 3, "2M", "GT"),
    ])
    res = run_cpu(text)
    recs = res.fastas["ref1"]
    assert len(recs) == 1
    assert recs[0].seq == "ACGT------"
    # sumcov = 1+1+2+2 = 6; len = 10 -> coverage 0.6; length strips "-" -> 4
    assert recs[0].header == (">p|c25 reference:ref1 coverage:0.6 length:4"
                              " consensus_threshold:25%")


def test_tie_groups_all_or_nothing():
    # one position: A:2, C:2, T:1 -> groups [[4,[A,C]],[1,[T]]]
    text = sam_text([("r", 1)], [
        ("r", 1, "1M", "A"), ("r", 1, "1M", "A"),
        ("r", 1, "1M", "C"), ("r", 1, "1M", "C"),
        ("r", 1, "1M", "T"),
    ])
    # t=0.5: cutoff 2.5 -> take {A,C} (total 4), stop -> "M"
    assert run_cpu(text, thresholds=[0.5]).fastas["r"][0].seq == "M"
    # t=0.9: cutoff 4.5 -> take {A,C} (4 < 4.5) then {T} -> "ACT" -> "H"
    assert run_cpu(text, thresholds=[0.9]).fastas["r"][0].seq == "H"
    # t=0.25: cutoff 1.25 -> take {A,C}, stop -> "M"
    assert run_cpu(text, thresholds=[0.25]).fastas["r"][0].seq == "M"


def test_multi_threshold_record_order():
    text = sam_text([("r", 2)], [("r", 1, "2M", "AC")])
    res = run_cpu(text, thresholds=[0.25, 0.75, 0.5])
    labels = [r.header.split("|c")[1].split(" ")[0] for r in res.fastas["r"]]
    assert labels == ["25", "75", "50"]
    assert all(r.seq == "AC" for r in res.fastas["r"])


def test_gap_majority_yields_gap_char_and_length_drop():
    # 1 read with a counted deletion: gaps win the vote -> "-" in sequence.
    text = sam_text([("r", 4)], [("r", 1, "1M2D1M", "AT")])
    res = run_cpu(text)
    assert res.fastas["r"][0].seq == "A--T"
    # length strips gaps: 2
    assert "length:2" in res.fastas["r"][0].header


def test_maxdel_gate_skips_gap_bases_but_advances():
    text = sam_text([("r", 8)], [("r", 1, "2M3D2M", "ACGT")])
    # gaps total 3 > maxdel 2 -> gap bases not counted -> cov 0 at pos 2..4
    res = run_cpu(text, maxdel=2)
    assert res.fastas["r"][0].seq == "AC---GT-"
    # sumcov = 4 covered positions -> coverage round(4/8,2)=0.5
    assert "coverage:0.5" in res.fastas["r"][0].header
    # default maxdel=150 -> gaps counted -> vote emits "-" at pos 2..4 (same
    # text here, but coverage differs: sumcov=7)
    res2 = run_cpu(text)
    assert res2.fastas["r"][0].seq == "AC---GT-"
    assert "coverage:0.88" in res2.fastas["r"][0].header  # round(7/8,2)


def test_maxdel_none_means_gate_disabled():
    text = sam_text([("r", 8)], [("r", 1, "2M3D2M", "ACGT")])
    res = run_cpu(text, maxdel=None)
    assert "coverage:0.88" in res.fastas["r"][0].header


def test_min_depth_fills_shallow_positions():
    text = sam_text([("r", 3)], [
        ("r", 1, "3M", "ACG"),
        ("r", 1, "1M", "A"),
    ])
    res = run_cpu(text, min_depth=2)
    assert res.fastas["r"][0].seq == "A--"
    # sumcov counts sub-min-depth covered positions too (spec :357): 2+1+1=4
    assert "coverage:1.33" in res.fastas["r"][0].header  # round(4/3,2)


def test_fill_character_and_length_interaction():
    # Quirk 10: fill "N" counts toward the length: field (only "-" stripped).
    text = sam_text([("r", 5)], [("r", 1, "2M", "AC")])
    res = run_cpu(text, fill="N")
    assert res.fastas["r"][0].seq == "ACNNN"
    assert "length:5" in res.fastas["r"][0].header


def test_zero_coverage_reference_pruned():
    text = sam_text([("covered", 2), ("empty", 5)], [("covered", 1, "2M", "AC")])
    res = run_cpu(text, fill="N")
    assert "covered" in res.fastas
    assert "empty" not in res.fastas  # pruned even though fill would be "N"


def test_all_gap_consensus_dropped():
    text = sam_text([("r", 5)], [("r", 1, "5D", "A")])
    res = run_cpu(text)
    assert res.fastas == {}


def test_insertion_basic_placement_and_case():
    # 3 reads AAA; 1 read with "CC" inserted between pos1 and pos2
    text = sam_text([("r", 6)], [
        ("r", 1, "3M", "AAA"), ("r", 1, "3M", "AAA"), ("r", 1, "3M", "AAA"),
        ("r", 1, "2M2I1M", "AACCA"),
    ])
    # t=0.25: cutoff 1.0 at cov 4; ins col: {-:3, C:1} -> take gap group,
    # call "-" -> skipped entirely
    res = run_cpu(text, thresholds=[0.25])
    assert res.fastas["r"][0].seq == "AAA---"
    # t=1.0: cutoff 4.0 -> take gap (3<4) then C -> {-,C} -> "c";
    # two columns appended after the base at pos 2 (right-shift, quirk 3)
    res2 = run_cpu(text, thresholds=[1.0])
    assert res2.fastas["r"][0].seq == "AAAcc---"
    # sumcov = 4*3 + 4 + 4 = 20, len 8 -> 2.5; length strips "-" -> 5
    assert "coverage:2.5" in res2.fastas["r"][0].header
    assert "length:5" in res2.fastas["r"][0].header


def test_insertion_majority_uppercase():
    # insertion supported by 3 of 4 reads: col {-:1, C:3} -> t=0.5 cutoff 2
    # -> take C group (3 >= 2) -> "C" uppercase.  The motif is recorded at
    # start_ref=2 and emitted AFTER the base at index 2 (right-shift quirk 3),
    # so the biological "AACAA" comes out as "AAACA".
    text = sam_text([("r", 4)], [
        ("r", 1, "2M1I2M", "AACAA"),
        ("r", 1, "2M1I2M", "AACAA"),
        ("r", 1, "2M1I2M", "AACAA"),
        ("r", 1, "4M", "AAAA"),
    ])
    res = run_cpu(text, thresholds=[0.5])
    assert res.fastas["r"][0].seq == "AAACA"


def test_insertion_negative_gap_count_survives():
    # Quirk 4: inserting read contributes no coverage at the key position.
    # read: 1M2I at pos 1 -> insert key = 1, cov[1] = 0 -> gap count -1.
    # Position 1 has zero coverage -> fill; insertion never emitted.
    text = sam_text([("r", 2)], [("r", 1, "1M2I", "ACC")])
    res = run_cpu(text)
    assert res.fastas["r"][0].seq == "A-"


def test_insertion_not_emitted_below_min_depth():
    # Quirk 8: insertion emission is nested inside the min_depth branch.
    text = sam_text([("r", 3)], [("r", 1, "1M1I2M", "ACAA")])
    res = run_cpu(text, min_depth=2)
    # every position is below min_depth -> all-fill sequence -> record dropped
    # entirely (sam2consensus.py:400-406)
    assert res.fastas == {}
    # min_depth=1: ins col at key 1 is {C:1, -:0}; the zero gap count is
    # filtered (value != 0), so C wins -> emitted after the base at index 1
    # (right-shift): "AACA"
    res2 = run_cpu(text, min_depth=1)
    assert res2.fastas["r"][0].seq == "AACA"


def test_insertion_at_contig_end_never_emitted():
    # insert key == reflength: exists in the table but the emit loop stops at
    # reflength-1 (the reference would IndexError during gap completion; we
    # complete with cov 0 and never emit — divergence documented in cpu.py).
    text = sam_text([("r", 2)], [("r", 1, "2M2I", "AACC")])
    res = run_cpu(text)
    assert res.fastas["r"][0].seq == "AA"


def test_n_bases_count_and_lowercase_calls():
    # N competes in the vote; {A,N} tie -> "AN" -> lowercase "a"
    text = sam_text([("r", 1)], [("r", 1, "1M", "A"), ("r", 1, "1M", "N")])
    res = run_cpu(text, thresholds=[1.0])
    assert res.fastas["r"][0].seq == "a"


def test_negative_pos_wraps_like_python_list():
    # POS=0 => pos_ref=-1; Python list indexing wraps to the contig's end.
    text = sam_text([("r", 4)], [("r", 0, "2M", "AC"), ("r", 1, "1M", "G")])
    res = run_cpu(text)
    # read1: A at index -1 (=3), C at index 0; read2: G at index 0
    # pos0: C:1,G:1 tie -> t=.25 cutoff .5 -> take {C,G} -> "S"
    assert res.fastas["r"][0].seq == "S--A"


def test_unknown_reference_strict_raises_permissive_skips():
    text = sam_text([("r", 2)], [("other", 1, "2M", "AC"), ("r", 1, "2M", "AC")])
    with pytest.raises(KeyError):
        run_cpu(text)
    res = run_cpu(text, strict=False)
    assert res.fastas["r"][0].seq == "AC"
    assert res.stats.reads_skipped == 1


def test_out_of_alphabet_base_strict_raises():
    text = sam_text([("r", 2)], [("r", 1, "2M", "ac")])
    with pytest.raises(KeyError):
        run_cpu(text)
    res = run_cpu(text, strict=False)
    assert res.fastas == {}


def test_read_overrunning_contig_strict_raises():
    text = sam_text([("r", 3)], [("r", 2, "3M", "ACG")])
    with pytest.raises(IndexError):
        run_cpu(text)


def test_unmapped_star_cigar_skipped():
    text = sam_text([("r", 2)], [("r", 1, "*", "*"), ("r", 1, "2M", "AC")])
    res = run_cpu(text)
    assert res.stats.reads_mapped == 1
    assert res.fastas["r"][0].seq == "AC"


def test_duplicate_sq_lines_last_length_wins():
    # Reference: each @SQ reallocates via dict assignment, so the last LN
    # wins; must not crash the reformat pass.
    text = sam_text([("r", 3), ("r", 5)], [("r", 1, "2M", "AC")])
    res = run_cpu(text)
    assert res.fastas["r"][0].seq == "AC---"


def test_permissive_skip_leaves_no_partial_counts():
    # An out-of-bounds read must contribute nothing when skipped.
    text = sam_text([("r", 3)], [
        ("r", 2, "3M", "GGG"),   # spans [1,4) past the end -> skipped
        ("r", 1, "2M", "AC"),
    ])
    res = run_cpu(text, strict=False)
    assert res.stats.reads_skipped == 1
    assert res.fastas["r"][0].seq == "AC-"
