"""Pallas insertion-table kernel vs the scatter oracle (interpret mode).

The kernel (ops/pallas_insertion.py) must reproduce
``ops.insertions.build_insertion_table`` exactly for any event set:
unsorted keys, duplicate (key, col, code) events, keys straddling
key-block boundaries, event counts straddling event-block boundaries, and
empty/padded tails.  Interpret mode runs the real kernel logic on CPU
(SURVEY.md §4 "Pallas kernels get an interpreter-mode test path").
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from sam2consensus_tpu.ops.insertions import build_insertion_table  # noqa: E402
from sam2consensus_tpu.ops.pallas_insertion import (  # noqa: E402
    EVENT_BLOCK, KEY_BLOCK, build_insertion_table_pallas)


def _oracle(ev_key, ev_col, ev_code, k, c):
    table = jnp.zeros((k, c, 6), dtype=jnp.int32)
    return np.asarray(build_insertion_table(
        table, jnp.asarray(ev_key), jnp.asarray(ev_col),
        jnp.asarray(ev_code)))


@pytest.mark.parametrize("k,c,e", [
    (1, 1, 1),                          # minimal
    (5, 3, 40),                         # tiny, duplicates guaranteed
    (KEY_BLOCK + 7, 2, EVENT_BLOCK + 33),   # straddles both block sizes
    (3, 22, 2 * EVENT_BLOCK),           # wide columns, many events
])
def test_pallas_table_matches_scatter(k, c, e):
    rng = np.random.default_rng(k * 1000 + e)
    ev_key = rng.integers(0, k, e).astype(np.int32)
    ev_col = rng.integers(0, c, e).astype(np.int32)
    ev_code = rng.integers(0, 6, e).astype(np.int32)
    got = build_insertion_table_pallas(ev_key, ev_col, ev_code, k, c,
                                       interpret=True)
    assert np.array_equal(np.asarray(got), _oracle(ev_key, ev_col,
                                                   ev_code, k, c))


def test_pallas_table_hot_key():
    """Every event on one key: the CSR ranges collapse to one block run."""
    k, c, e = 200, 4, 3 * EVENT_BLOCK
    ev_key = np.full(e, 137, dtype=np.int32)
    ev_col = np.tile(np.arange(c), e // c + 1)[:e].astype(np.int32)
    ev_code = np.tile(np.arange(6), e // 6 + 1)[:e].astype(np.int32)
    got = build_insertion_table_pallas(ev_key, ev_col, ev_code, k, c,
                                       interpret=True)
    oracle = _oracle(ev_key, ev_col, ev_code, k, c)
    assert np.array_equal(np.asarray(got), oracle)
    assert oracle.sum() == e


def test_pallas_table_key_block_boundary():
    """Keys exactly at multiples of KEY_BLOCK land in the right blocks."""
    k = 3 * KEY_BLOCK
    c = 2
    keys = np.array([0, KEY_BLOCK - 1, KEY_BLOCK, 2 * KEY_BLOCK - 1,
                     2 * KEY_BLOCK, k - 1], dtype=np.int32)
    ev_key = np.repeat(keys, 5)
    ev_col = np.tile(np.arange(c), len(ev_key) // c + 1)[: len(ev_key)]
    ev_col = ev_col.astype(np.int32)
    ev_code = np.ones(len(ev_key), dtype=np.int32)
    got = build_insertion_table_pallas(ev_key, ev_col, ev_code, k, c,
                                       interpret=True)
    assert np.array_equal(np.asarray(got),
                          _oracle(ev_key, ev_col, ev_code, k, c))


def test_end_to_end_pallas_vs_cpu_backend():
    """Full jax backend with --insertion-kernel pallas == CPU oracle."""
    import io

    from sam2consensus_tpu.backends.cpu import CpuBackend
    from sam2consensus_tpu.backends.jax_backend import JaxBackend
    from sam2consensus_tpu.config import RunConfig
    from sam2consensus_tpu.io.fasta import render_file
    from sam2consensus_tpu.io.sam import iter_records, read_header
    from sam2consensus_tpu.utils.simulate import SimSpec, simulate

    text = simulate(SimSpec(n_contigs=4, contig_len=200, n_reads=600,
                            read_len=40, ins_read_rate=0.3,
                            del_read_rate=0.1, max_indel=5, seed=13))

    def rendered(backend, cfg):
        handle = io.StringIO(text)
        contigs, _n, first = read_header(handle)
        res = backend.run(contigs, iter_records(handle, first), cfg)
        return {n: render_file(r, 0) for n, r in res.fastas.items()}

    cfg_cpu = RunConfig(prefix="p", thresholds=[0.25, 0.75])
    cfg_pal = RunConfig(prefix="p", thresholds=[0.25, 0.75],
                        ins_kernel="pallas")
    out_cpu = rendered(CpuBackend(), cfg_cpu)
    out_pal = rendered(JaxBackend(), cfg_pal)
    assert out_pal == out_cpu
