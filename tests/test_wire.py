"""The delta8 wire codec + staging pipeline (sam2consensus_tpu/wire).

Four contracts pinned here:

* **round trip** — host encode → host/device decode reproduces the
  exact ``(starts, codes)`` operands for adversarial position patterns:
  unsorted tails, >254 deltas, single-row slabs, all-PAD rows, interior
  gap/N/PAD cells, odd widths (property-based under hypothesis when the
  ``[dev]`` extra is installed);
* **byte identity** — delta8 vs packed5 produce identical counts on the
  single-device accumulator and across the cpu-mesh dp/sp/dpsp layouts,
  and identical FASTA end-to-end through the jax backend;
* **decisions** — ``--wire auto`` resolves from the measured link
  constants exactly like the tail-placement gates (decision table
  pinned), and the shard-mode model prices post-codec bytes;
* **resilience** — a ``wire_encode`` fault on the staging thread
  invalidates the slot and replays the batch unstaged; a persistent
  fault demotes through the ladder, pinning the codec off at the first
  rung, with counts still exact.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from sam2consensus_tpu.constants import PAD_CODE  # noqa: E402
from sam2consensus_tpu.encoder.events import SegmentBatch  # noqa: E402
from sam2consensus_tpu.ops.pileup import (PileupAccumulator,  # noqa: E402
                                          encode_wire_slab, pack_nibbles)
from sam2consensus_tpu.resilience import faultinject  # noqa: E402
from sam2consensus_tpu.wire import codec as wc  # noqa: E402
from sam2consensus_tpu.wire import device as wd  # noqa: E402
from sam2consensus_tpu.wire.pipeline import (StageSlots,  # noqa: E402
                                             _intersect_sec)

ACGT = np.array([1, 2, 3, 5], dtype=np.uint8)


def _roundtrip(starts, codes, chunks=1):
    starts = np.asarray(starts, dtype=np.int32)
    codes = np.asarray(codes, dtype=np.uint8)
    slab = wc.encode_slab(starts, codes, chunks=chunks)
    assert slab is not None
    s2, c2 = wc.decode_slab_host(slab)
    np.testing.assert_array_equal(s2, starts)
    np.testing.assert_array_equal(c2, codes)
    sd, pk = wd.decode_to_packed(
        *[np.asarray(a) for a in slab.arrays()],
        width=slab.width, sentinel=slab.sentinel)
    np.testing.assert_array_equal(np.asarray(sd), starts)
    np.testing.assert_array_equal(np.asarray(pk), pack_nibbles(codes))
    return slab


def _random_slab(rng, s, w, esc_rate=0.02):
    starts = np.sort(rng.integers(0, 1 << 20, s)).astype(np.int32)
    codes = rng.choice(ACGT, (s, w)).astype(np.uint8)
    if esc_rate:
        m = rng.random((s, w)) < esc_rate
        codes[m] = rng.choice([0, 4], int(m.sum()))  # gaps and Ns
    for r in range(s):
        t = int(rng.integers(0, w // 2 + 1))
        if t:
            codes[r, w - t:] = PAD_CODE
    return starts, codes


class TestRoundTrip:
    def test_sorted_clean(self):
        rng = np.random.default_rng(0)
        _roundtrip(*_random_slab(rng, 64, 128))

    def test_unsorted_tail(self):
        rng = np.random.default_rng(1)
        starts, codes = _random_slab(rng, 32, 64)
        starts[-3:] = [7, 1 << 19, 0]          # out-of-order tail
        slab = _roundtrip(starts, codes)
        assert slab.n_esc_rows >= 2            # negative deltas escaped

    def test_large_deltas_escape(self):
        starts = np.array([0, 100, 100 + 254, 100 + 254 + 255,
                           1 << 30], dtype=np.int32)
        codes = np.tile(ACGT, (5, 8))
        slab = _roundtrip(starts, codes)
        # delta 255 and the 2^30 jump must both ride the escape lane
        assert slab.n_esc_rows >= 2

    def test_single_row_slab(self):
        _roundtrip([12345], np.tile(ACGT, (1, 8)))

    def test_all_pad_rows(self):
        rng = np.random.default_rng(2)
        starts, codes = _random_slab(rng, 16, 32)
        codes[3, :] = PAD_CODE
        codes[15, :] = PAD_CODE
        starts[3] = 0                           # encoder pad-row shape
        _roundtrip(starts, codes)

    def test_interior_escapes(self):
        starts = np.arange(4, dtype=np.int32) * 10
        codes = np.tile(ACGT, (4, 4))
        codes[0, 1] = 0                         # gap
        codes[1, 2] = 4                         # N
        codes[2, 3] = PAD_CODE                  # interior PAD (maxdel)
        codes[2, -1] = 1                        # ...kept inside payload
        slab = _roundtrip(starts, codes)
        assert slab.n_esc_cells == 3

    def test_odd_width(self):
        rng = np.random.default_rng(3)
        _roundtrip(*_random_slab(rng, 8, 33))

    def test_chunked(self):
        rng = np.random.default_rng(4)
        starts, codes = _random_slab(rng, 64, 32)
        for chunks in (2, 4, 8):
            _roundtrip(starts, codes, chunks=chunks)

    def test_uneven_chunks_refused(self):
        rng = np.random.default_rng(5)
        starts, codes = _random_slab(rng, 10, 32)
        assert wc.encode_slab(starts, codes, chunks=3) is None

    def test_header_self_describing(self):
        rng = np.random.default_rng(6)
        slab = _roundtrip(*_random_slab(rng, 16, 64), chunks=4)
        h = slab.header()
        assert h[0] == wc.CODECS.index("delta8")
        assert h[1] == 16 and h[2] == 64 and h[3] == 4
        assert slab.wire_bytes >= h.nbytes

    def test_escape_dense_not_worthwhile(self):
        # every cell a gap: the escape list costs more than packed5
        starts = np.arange(16, dtype=np.int32)
        codes = np.zeros((16, 32), dtype=np.uint8)
        slab = wc.encode_slab(starts, codes)
        assert not wc.worthwhile(slab)
        assert encode_wire_slab("delta8", starts, codes) is None

    def test_compresses_representative_slab(self):
        # the tentpole's bread-and-butter shape: ~100 bp reads in the
        # 128-wide bucket at real coverage density (mean start delta
        # well under 255), ~0.5% non-ACGT cells — the north_star
        # acceptance bar is >= 2x on this shape
        rng = np.random.default_rng(7)
        starts = np.sort(
            rng.integers(0, 1024 * 100, 1024)).astype(np.int32)
        codes = rng.choice(ACGT, (1024, 128)).astype(np.uint8)
        codes[rng.random((1024, 128)) < 0.005] = 0
        codes[:, 100:] = PAD_CODE
        slab = _roundtrip(starts, codes)
        assert wc.packed5_slab_bytes(1024, 128) / slab.wire_bytes >= 2.0

    def test_canonicalize_makes_unsorted_delta_friendly(self):
        # random read order would escape every delta; the canonical
        # sort restores uint8 deltas, with the pad tail kept a suffix
        rng = np.random.default_rng(8)
        starts = rng.integers(0, 1024 * 100, 1024).astype(np.int32)
        codes = rng.choice(ACGT, (1024, 128)).astype(np.uint8)
        codes[:, 100:] = PAD_CODE            # ~100 bp payloads
        codes[-16:] = PAD_CODE               # encoder pow2 pad tail
        starts[-16:] = 0
        s2, c2 = wc.canonicalize_rows(starts, codes)
        assert np.array_equal(np.sort(starts[:-16]), s2[:-16])
        assert (c2[-16:] == PAD_CODE).all()
        slab = wc.encode_slab(s2, c2)
        # sorted deltas are mostly uint8; unsorted would escape ~all
        # 1024 rows (every delta random-signed)
        assert slab.n_esc_rows < 1024 * 0.2
        assert wc.packed5_slab_bytes(1024, 128) / slab.wire_bytes >= 2.0
        # already-sorted inputs pass through untouched (same objects)
        s3, c3 = wc.canonicalize_rows(s2, c2)
        assert s3 is s2 and c3 is c2


try:
    from hypothesis import given, settings, strategies as st

    @given(st.data())
    @settings(max_examples=40, deadline=None)
    def test_round_trip_property(data):
        """Property-based round trip over arbitrary position patterns
        and code matrices (incl. PAD everywhere, any symbol byte)."""
        s = data.draw(st.integers(1, 24))
        w = data.draw(st.integers(1, 40))
        chunks = data.draw(st.sampled_from(
            [c for c in (1, 2, 3, 4, 6, 8) if s % c == 0]))
        starts = np.array(
            data.draw(st.lists(st.integers(0, 2**31 - 1),
                               min_size=s, max_size=s)), dtype=np.int32)
        codes = np.array(
            data.draw(st.lists(
                st.lists(st.sampled_from([0, 1, 2, 3, 4, 5, 255]),
                         min_size=w, max_size=w),
                min_size=s, max_size=s)), dtype=np.uint8)
        slab = wc.encode_slab(starts, codes, chunks=chunks)
        s2, c2 = wc.decode_slab_host(slab)
        np.testing.assert_array_equal(s2, starts)
        np.testing.assert_array_equal(c2, codes)
except ImportError:  # pragma: no cover - [dev] extra not installed
    pass


class TestAccumulatorIdentity:
    def _batch(self, rng, total_len, s=512, w=128):
        # UNSORTED read order (the canonical sort is part of the path)
        starts = rng.integers(0, total_len - w, s).astype(np.int32)
        codes = rng.choice(ACGT, (s, w)).astype(np.uint8)
        codes[rng.random((s, w)) < 0.005] = 0
        codes[:, 100:] = PAD_CODE
        return SegmentBatch(buckets={w: (starts, codes)})

    def test_single_device_identity_and_ratio(self):
        total_len = 1 << 15
        mk = lambda: self._batch(np.random.default_rng(11), total_len)
        a_p5 = PileupAccumulator(total_len, strategy="scatter",
                                 wire="packed5")
        a_d8 = PileupAccumulator(total_len, strategy="scatter",
                                 wire="delta8")
        a_p5.add(mk())
        a_d8.add(mk())
        np.testing.assert_array_equal(a_p5.counts_host(),
                                      a_d8.counts_host())
        # the acceptance bar: the wire bill drops >= 2x on the
        # representative slab shape
        assert a_p5.bytes_h2d / a_d8.bytes_h2d >= 2.0
        assert a_d8.strategy_used.get("wire_delta8", 0) == 1

    def test_staged_identity(self):
        total_len = 1 << 15
        mk = lambda: self._batch(np.random.default_rng(12), total_len)
        a_ref = PileupAccumulator(total_len, strategy="scatter",
                                  wire="packed5")
        a_ref.add(mk())
        acc = PileupAccumulator(total_len, strategy="scatter",
                                wire="delta8")
        batch = mk()
        acc.stage(batch)
        assert batch.staged and list(batch.staged.values())[0].codec \
            == "delta8"
        acc.add(batch)
        np.testing.assert_array_equal(a_ref.counts_host(),
                                      acc.counts_host())


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs 8 (virtual) devices")
class TestShardedIdentity:
    """--wire delta8 is byte-identical across the cpu-mesh layouts."""

    def _payload(self, total_len):
        rng = np.random.default_rng(20)
        batches = []
        for _ in range(2):
            starts = np.sort(
                rng.integers(0, total_len - 64, 1500)).astype(np.int32)
            codes = rng.choice(
                np.array([1, 2, 3, 5, 0, 4], np.uint8), (1500, 64),
                p=[.24, .24, .24, .24, .02, .02]).astype(np.uint8)
            codes[:, 50:] = PAD_CODE
            batches.append({64: (starts, codes)})
        return batches

    def _oracle(self, total_len, payload):
        acc = PileupAccumulator(total_len, strategy="scatter",
                                wire="packed5")
        for buckets in payload:
            acc.add(SegmentBatch(buckets=dict(buckets)))
        return acc.counts_host()

    @pytest.mark.parametrize("mode", ["dp", "sp", "dpsp"])
    def test_layout_identity(self, mode):
        from jax.sharding import Mesh

        from sam2consensus_tpu.parallel.dp import ShardedConsensus
        from sam2consensus_tpu.parallel.dpsp import \
            ProductShardedConsensus
        from sam2consensus_tpu.parallel.mesh import make_mesh
        from sam2consensus_tpu.parallel.sp import \
            PositionShardedConsensus

        total_len = 1 << 16
        payload = self._payload(total_len)
        want = self._oracle(total_len, payload)
        if mode == "dp":
            acc = ShardedConsensus(make_mesh(8), total_len,
                                   pileup="scatter", wire="delta8")
        elif mode == "sp":
            acc = PositionShardedConsensus(
                make_mesh(8), total_len, halo=64, pileup="scatter",
                wire="delta8")
        else:
            mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 4),
                        ("dp", "sp"))
            acc = ProductShardedConsensus(mesh, total_len, halo=64,
                                          pileup="scatter", wire="delta8")
        for buckets in payload:
            acc.add(SegmentBatch(buckets=dict(buckets)))
        np.testing.assert_array_equal(acc.counts_host(), want)
        assert acc.bytes_h2d > 0


class TestDecisions:
    """--wire auto pinned to the link model, like the tail gates."""

    def test_forced_modes_win(self):
        assert wc.resolve_codec("delta8", None, link_free=True)[0] \
            == "delta8"
        assert wc.resolve_codec("packed5", 1e6, link_free=False)[0] \
            == "packed5"

    def test_auto_tunnel_compresses(self):
        codec, reason = wc.resolve_codec("auto", 40e6, link_free=False)
        assert (codec, reason) == ("delta8", "slow_link")

    def test_auto_pcie_ships_packed5(self):
        codec, reason = wc.resolve_codec("auto", 2e9, link_free=False)
        assert (codec, reason) == ("packed5", "fast_link")

    def test_auto_link_free_ships_packed5(self):
        codec, reason = wc.resolve_codec("auto", None, link_free=True)
        assert (codec, reason) == ("packed5", "link_free")

    def test_env_override(self, monkeypatch):
        monkeypatch.setenv("S2C_WIRE", "delta8")
        assert wc.resolve_codec("auto", 2e9, link_free=False)[0] \
            == "delta8"

    def test_bad_mode_raises(self):
        with pytest.raises(ValueError):
            wc.resolve_codec("gzip", 40e6)

    def test_cutoff_between_tunnel_and_pcie(self):
        cut = wc.wire_auto_cutoff_bps()
        assert 40e6 < cut < 2e9

    def test_slab_stats_prices_post_codec_bytes(self):
        from sam2consensus_tpu.parallel.auto import slab_stats

        rng = np.random.default_rng(30)
        starts = np.sort(rng.integers(0, 1 << 20, 256)).astype(np.int32)
        codes = rng.choice(ACGT, (256, 128)).astype(np.uint8)
        buckets = {128: (starts, codes)}
        _r, rb_p5, _w, _i, _s = slab_stats(buckets, 1 << 20,
                                           wire="packed5")
        _r, rb_d8, _w, _i, _s = slab_stats(buckets, 1 << 20,
                                           wire="delta8")
        assert rb_d8 < rb_p5 / 1.8


class TestResilienceWire:
    def _batch(self, total_len):
        rng = np.random.default_rng(40)
        starts = np.sort(
            rng.integers(0, total_len - 64, 256)).astype(np.int32)
        codes = rng.choice(ACGT, (256, 64)).astype(np.uint8)
        return SegmentBatch(buckets={64: (starts, codes)})

    def test_stage_failure_invalidates_slot_and_replays(self):
        """One counted wire_encode fault on the staging path: the slot
        is invalidated, the batch delivers unstaged, and the consumer's
        own encode (fault budget exhausted) lands exact counts."""
        total_len = 1 << 14
        want_acc = PileupAccumulator(total_len, strategy="scatter",
                                     wire="packed5")
        want_acc.add(self._batch(total_len))
        acc = PileupAccumulator(total_len, strategy="scatter",
                                wire="delta8")
        batch = self._batch(total_len)
        stager = StageSlots(acc.stage)
        faultinject.configure("wire_encode:fatal:0:1")
        try:
            with pytest.raises(faultinject.InjectedFatalError):
                stager.stage(batch)
            batch.staged.clear()       # what the prefetcher does
            # slot was released by the stager on failure: a second
            # batch can still stage without blocking
            acc.add(batch)             # consumer replay, unstaged
        finally:
            faultinject.configure("")
        np.testing.assert_array_equal(acc.counts_host(),
                                      want_acc.counts_host())

    def test_persistent_fault_demotes_and_pins_codec_off(self):
        """A persistent wire_encode fatal under --on-device-error
        fallback walks ONE ladder rung: the codec pins to packed5 and
        the run finishes on the device scatter, counts exact."""
        from sam2consensus_tpu.resilience.ladder import \
            ResilientDispatcher
        from sam2consensus_tpu.resilience.policy import RetryPolicy

        total_len = 1 << 14
        want_acc = PileupAccumulator(total_len, strategy="scatter",
                                     wire="packed5")
        want_acc.add(self._batch(total_len))
        acc = PileupAccumulator(total_len, strategy="scatter",
                                wire="delta8")
        policy = RetryPolicy(retries=1, backoff=0.0, on_error="fallback")
        disp = ResilientDispatcher(policy, total_len)
        faultinject.configure("wire_encode:fatal:0:inf")
        try:
            acc = disp.add(acc, self._batch(total_len))
        finally:
            faultinject.configure("")
        assert disp.demotions >= 1
        assert acc.wire == "packed5"
        assert not isinstance(acc, type(None))
        np.testing.assert_array_equal(acc.counts_host(),
                                      want_acc.counts_host())


class TestWireAccounting:
    def test_staged_slab_billed_once_across_replays(self):
        """A retry/ladder replay re-consumes the SAME staged operands
        without the bytes re-crossing the link: bill once."""
        total_len = 1 << 14
        rng = np.random.default_rng(50)
        starts = np.sort(
            rng.integers(0, total_len - 64, 128)).astype(np.int32)
        codes = rng.choice(ACGT, (128, 64)).astype(np.uint8)
        acc = PileupAccumulator(total_len, strategy="scatter",
                                wire="delta8")
        batch = SegmentBatch(buckets={64: (starts, codes)})
        acc.stage(batch)
        staged = batch.staged[64]
        first = acc._consume_slab(staged)
        once = acc.bytes_h2d
        again = acc._consume_slab(staged)          # replay attempt
        assert acc.bytes_h2d == once
        assert acc.strategy_used.get("wire_delta8", 0) == 1
        np.testing.assert_array_equal(np.asarray(first[0]),
                                      np.asarray(again[0]))


class TestStagePipeline:
    def test_interval_intersection(self):
        a = [(0.0, 2.0), (5.0, 6.0)]
        b = [(1.0, 3.0), (5.5, 5.75), (10.0, 11.0)]
        assert _intersect_sec(a, b) == pytest.approx(1.25)
        assert _intersect_sec([], b) == 0.0

    def test_backpressure_two_slots(self):
        staged = []
        stager = StageSlots(staged.append, slots=2)
        b1, b2, b3 = object(), object(), object()
        stager.stage(b1)
        stager.stage(b2)
        assert len(staged) == 2
        # third stage would block: release one slot first
        stager.consumed(b1)
        stager.stage(b3)
        assert len(staged) == 3
        stager.consumed(b2)
        stager.consumed(b3)
        stager.close()

    def test_overlap_accounting(self):
        stager = StageSlots(lambda b: None)
        stager._stage_iv = [(0.0, 1.0)]
        stager.note_consume(0.5, 2.0)
        assert stager.overlap_sec() == pytest.approx(0.5)
        assert stager.stage_sec() == pytest.approx(1.0)

    def test_staging_rearms_after_transient_failure(self):
        """One transient staging fault must not kill the pipeline for
        the rest of the run: the prefetcher re-arms on the next batch
        and only MAX_STAGE_FAILURES consecutive failures disable it."""
        from sam2consensus_tpu.backends.jax_backend import _Prefetcher

        total_len = 1 << 14
        rng = np.random.default_rng(51)

        def mk():
            starts = np.sort(
                rng.integers(0, total_len - 64, 64)).astype(np.int32)
            codes = rng.choice(ACGT, (64, 64)).astype(np.uint8)
            return SegmentBatch(buckets={64: (starts, codes)})

        acc = PileupAccumulator(total_len, strategy="scatter",
                                wire="delta8")
        stager = StageSlots(acc.stage)
        batches = [mk() for _ in range(4)]
        # fault only the FIRST wire encode; later batches stage fine
        faultinject.configure("wire_encode:fatal:0:1")
        try:
            pf = _Prefetcher(iter(batches), stager=stager)
            seen = []
            for b in pf:
                seen.append(b)
                stager.consumed(b)
            assert len(seen) == 4
            # batch 0 delivered unstaged (slot invalidated), the rest
            # re-armed and staged
            assert not seen[0].staged
            assert sum(bool(b.staged) for b in seen[1:]) == 3
        finally:
            faultinject.configure("")
            stager.close()


class TestEndToEnd:
    def test_backend_byte_identity(self, tmp_path, monkeypatch):
        """--wire delta8 vs packed5 through the whole jax backend on
        the device pileup path: identical FASTA, smaller h2d bill."""
        from sam2consensus_tpu.backends.jax_backend import JaxBackend
        from sam2consensus_tpu.config import RunConfig
        from sam2consensus_tpu.io.sam import (ReadStream, opener,
                                              read_header)
        from sam2consensus_tpu.utils.simulate import (SimSpec, simulate,
                                                      write_sam)

        path = write_sam(
            simulate(SimSpec(n_contigs=2, contig_len=8000, n_reads=2000,
                             read_len=100, seed=99)),
            str(tmp_path / "wire.sam"))
        monkeypatch.setenv("S2C_HOST_PILEUP_MAX_LEN", "1")

        def run(wire):
            cfg = RunConfig(backend="jax", wire=wire, pileup="scatter")
            h = opener(path, binary=True)
            contigs, _n, first = read_header(h)
            res = JaxBackend().run(contigs, ReadStream(h, first), cfg)
            h.close()
            return res

        r_p5 = run("packed5")
        r_d8 = run("delta8")
        assert r_p5.fastas == r_d8.fastas
        assert r_d8.stats.extra["h2d_bytes"] \
            < r_p5.stats.extra["h2d_bytes"]
        assert r_d8.stats.extra["wire"]["chosen"] == "delta8"
