"""dp x sp product-mode tests on the 8 virtual CPU devices (conftest).

Round-4 verdict item 5: the 2-D mesh must COMPOSE — read shards across
``dp`` groups x macro position blocks across ``sp``, halo exchange over
sp, reduce-scatter over dp — byte-identically to the unsharded pipeline
on (2, 4) and (4, 2) meshes.
"""

import io

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from sam2consensus_tpu.backends.cpu import CpuBackend
from sam2consensus_tpu.backends.jax_backend import JaxBackend
from sam2consensus_tpu.config import RunConfig
from sam2consensus_tpu.encoder.events import GenomeLayout, ReadEncoder
from sam2consensus_tpu.io.fasta import render_file
from sam2consensus_tpu.io.sam import iter_records, read_header
from sam2consensus_tpu.ops.cutoff import encode_thresholds
from sam2consensus_tpu.ops.pileup import PileupAccumulator
from sam2consensus_tpu.parallel.dpsp import ProductShardedConsensus
from sam2consensus_tpu.utils.simulate import SimSpec, simulate


def _mesh(n_dp, n_sp):
    devs = np.asarray(jax.devices()[: n_dp * n_sp]).reshape(n_dp, n_sp)
    return Mesh(devs, ("dp", "sp"))


def _encode_all(text):
    handle = io.StringIO(text)
    contigs, _n, first = read_header(handle)
    layout = GenomeLayout(contigs)
    enc = ReadEncoder(layout)
    chunks = list(enc.encode_segments(iter_records(handle, first),
                                      chunk_reads=64))
    return layout, chunks


@pytest.mark.parametrize("n_dp,n_sp", [(2, 4), (4, 2)])
def test_product_counts_equal_single_device(n_dp, n_sp):
    text = simulate(SimSpec(n_contigs=4, contig_len=200, n_reads=500,
                            read_len=50, ins_read_rate=0.1,
                            del_read_rate=0.1, seed=61))
    layout, chunks = _encode_all(text)

    single = PileupAccumulator(layout.total_len)
    for c in chunks:
        single.add(c)
    expected = np.asarray(single.counts)

    # small halo so rows actually overhang macro blocks and wide rows split
    prod = ProductShardedConsensus(_mesh(n_dp, n_sp), layout.total_len,
                                   halo=32)
    for c in chunks:
        prod.add(c)
    np.testing.assert_array_equal(prod.counts_host(), expected)
    assert prod.rows_real > 0


def test_product_vote_and_tail_stats_match_flat_layout():
    text = simulate(SimSpec(n_contigs=3, contig_len=150, n_reads=400,
                            read_len=40, seed=62))
    layout, chunks = _encode_all(text)
    prod = ProductShardedConsensus(_mesh(2, 4), layout.total_len, halo=32)
    for c in chunks:
        prod.add(c)
    thr_enc = encode_thresholds([0.25, 0.75])
    syms = prod.vote(thr_enc, min_depth=1)

    import jax.numpy as jnp

    from sam2consensus_tpu.ops.vote import vote_positions
    syms1, _cov1 = vote_positions(jnp.asarray(prod.counts_host()),
                                  jnp.asarray(thr_enc), 1)
    np.testing.assert_array_equal(syms, np.asarray(syms1))

    counts = prod.counts_host()
    cov = counts.sum(axis=-1)
    offsets = layout.offsets.astype(np.int32)
    keys = np.asarray([0, 5, layout.total_len - 1], dtype=np.int32)
    contig_sums, site_cov = prod.tail_stats(offsets, keys)
    expect_sums = np.asarray(
        [cov[offsets[i]:offsets[i + 1]].sum()
         for i in range(len(layout.names))])
    np.testing.assert_array_equal(contig_sums, expect_sums)
    np.testing.assert_array_equal(site_cov, cov[keys])


def test_product_checkpoint_restore_roundtrip():
    layout_len = 700
    rng = np.random.default_rng(0)
    counts = rng.integers(0, 300, (layout_len, 6)).astype(np.int32)
    prod = ProductShardedConsensus(_mesh(2, 4), layout_len, halo=32)
    prod.restore(counts)
    np.testing.assert_array_equal(prod.counts_host(), counts)


def test_product_needs_true_2d_mesh():
    with pytest.raises(ValueError, match="2-D mesh"):
        ProductShardedConsensus(_mesh(1, 8), 1000, halo=32)
    with pytest.raises(ValueError, match="2-D mesh"):
        ProductShardedConsensus(_mesh(8, 1), 1000, halo=32)


def _run(text, backend, cfg):
    handle = io.StringIO(text)
    contigs, _n, first = read_header(handle)
    res = backend.run(contigs, iter_records(handle, first), cfg)
    return {n: render_file(r, 0) for n, r in res.fastas.items()}, res.stats


def test_backend_dpsp_byte_identical_to_oracle():
    text = simulate(SimSpec(n_contigs=3, contig_len=400, n_reads=800,
                            read_len=60, ins_read_rate=0.15,
                            del_read_rate=0.15, seed=63))
    cfg = RunConfig(prefix="t", thresholds=[0.25, 0.5], shards=1)
    out_cpu, _ = _run(text, CpuBackend(), cfg)
    cfg8 = RunConfig(prefix="t", thresholds=[0.25, 0.5], shards=8,
                     shard_mode="dpsp")
    out_dpsp, st = _run(text, JaxBackend(), cfg8)
    assert out_dpsp == out_cpu
    assert st.extra["shard_mode"] == "dpsp"
    assert st.extra["shards"] == 8
