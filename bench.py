#!/usr/bin/env python3
"""Benchmark: consensus bases/sec, jax backend vs the CPU golden baseline.

Prints ONE JSON line to stdout:
  {"metric": "consensus_bases_per_sec", "value": N, "unit": "bases/sec",
   "vs_baseline": N, "device": "...", "configs": [...], ...}

``value`` is the end-to-end jax-backend throughput (SAM text -> FASTA
records, warm compile) on the headline workload; ``vs_baseline`` is the
speedup over the CPU golden backend on the identical workload (BASELINE.md's
primary metric).  ``configs`` carries one row per BASELINE.md scenario
(phiX, multi-threshold, target capture, E. coli scale, insertion-heavy
amplicon — plus the Pallas-kernel variant of the amplicon) with per-phase
timings.  Every row asserts FASTA byte-identity between the two backends —
a benchmark that produced wrong bytes would be meaningless.

Robustness (round 1 ended with rc=1 and no number because jax.devices()
crashed in-process after the CPU baseline had already run):

* the accelerator is probed in a SUBPROCESS with a timeout and retries, so
  a hung/unavailable tunnel cannot hang or crash the bench itself;
* if the accelerator never comes up, the bench falls back to the XLA CPU
  backend, still reports the full result set, and marks the headline line
  with ``"device": "cpu-fallback"`` plus the probe's error tail;
* progress and per-config rows stream to stderr; stdout stays exactly one
  JSON line, emitted even on partial failure.

Env knobs: BENCH_SCALE (read-count multiplier, default 1.0), BENCH_CONFIGS
(comma-separated subset of config names), BENCH_READS / BENCH_CONTIGS /
BENCH_READ_LEN / BENCH_CONTIG_LEN (headline workload, defaults 200000 /
100 / 100 / 2000), BENCH_INIT_TIMEOUT (probe seconds, default 600),
BENCH_INIT_RETRIES (default 2).
"""

import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def probe_accelerator():
    """Try to initialize the default JAX backend in a subprocess.

    Returns (ok, platform, n_devices, diagnostics).  A subprocess probe
    cannot hang or kill the bench: a wedged tunnel hits the timeout and a
    crash stays in the child.
    """
    timeout = int(os.environ.get("BENCH_INIT_TIMEOUT", "600"))
    retries = int(os.environ.get("BENCH_INIT_RETRIES", "2"))
    here = os.path.dirname(os.path.abspath(__file__))
    # pin_platform_from_env: the environment's sitecustomize overrides
    # jax_platforms via jax.config, which silently trumps JAX_PLATFORMS —
    # without the pin, a JAX_PLATFORMS=cpu probe would still dial the
    # remote accelerator (round-1 failure mode)
    code = (f"import sys; sys.path.insert(0, {here!r}); "
            "from sam2consensus_tpu.utils.platform import "
            "pin_platform_from_env; pin_platform_from_env(); "
            "import jax; ds = jax.devices(); "
            "print('PROBE_OK', ds[0].platform, len(ds))")
    last_err = ""
    for attempt in range(1, retries + 1):
        log(f"[probe] attempt {attempt}/{retries} "
            f"(timeout {timeout}s, JAX_PLATFORMS="
            f"{os.environ.get('JAX_PLATFORMS', '<unset>')})")
        t0 = time.perf_counter()
        try:
            r = subprocess.run([sys.executable, "-c", code],
                               capture_output=True, text=True,
                               timeout=timeout)
        except subprocess.TimeoutExpired:
            last_err = f"probe timed out after {timeout}s"
            log(f"[probe] {last_err}")
            continue
        dt = time.perf_counter() - t0
        for line in r.stdout.splitlines():
            if line.startswith("PROBE_OK"):
                _tag, platform, n = line.split()
                log(f"[probe] backend up in {dt:.1f}s: "
                    f"{platform} x{n}")
                return True, platform, int(n), last_err
        last_err = (r.stderr.strip().splitlines() or ["no output"])[-1]
        log(f"[probe] failed after {dt:.1f}s (rc={r.returncode}): "
            f"{last_err}")
        if attempt < retries:
            time.sleep(min(60, 15 * attempt))
    return False, "", 0, last_err


def build_configs(n_devices: int):
    """Per-config rows pin ``shards=1`` so every row is a clean single-chip
    number (BASELINE.md's primary metric is bases/sec/chip); when more than
    one device is up, the headline also runs a ``sharded`` variant over all
    of them (shards=0) so the dp collective path gets a measured row."""
    from sam2consensus_tpu.utils.simulate import SimSpec

    scale = float(os.environ.get("BENCH_SCALE", "1.0"))

    def n(reads):
        return max(1000, int(reads * scale))

    # headline: the round-over-round comparable workload (same as round 1)
    headline_spec = SimSpec(
        n_contigs=int(os.environ.get("BENCH_CONTIGS", "100")),
        contig_len=int(os.environ.get("BENCH_CONTIG_LEN", "2000")),
        n_reads=n(int(os.environ.get("BENCH_READS", "200000"))),
        read_len=int(os.environ.get("BENCH_READ_LEN", "100")),
        ins_read_rate=0.05, del_read_rate=0.05, seed=42)

    # the five BASELINE.md scenarios (bench-scaled shapes; the spec-scaled
    # originals live in utils.simulate.BASELINE_SPECS for tests)
    return [
        # (name, spec, cfg_kwargs, jax_variants)
        ("headline", headline_spec, {"thresholds": [0.25]},
         {"sharded": {"shards": 0}} if n_devices > 1 else {}),
        ("phix", SimSpec(n_contigs=1, contig_len=5386, n_reads=n(20000),
                         read_len=100, seed=101, contig_prefix="phiX"),
         {"thresholds": [0.25]}, {}),
        ("phix_multithreshold",
         SimSpec(n_contigs=1, contig_len=5386, n_reads=n(20000),
                 read_len=100, seed=101, contig_prefix="phiX"),
         {"thresholds": [0.25, 0.50, 0.75]}, {}),
        ("target_capture",
         SimSpec(n_contigs=350, contig_len=1200, n_reads=n(100000),
                 read_len=100, seed=202, contig_prefix="gene"),
         {"thresholds": [0.25]}, {}),
        ("ecoli_scale",
         SimSpec(n_contigs=1, contig_len=4_600_000, n_reads=n(150000),
                 read_len=100, contig_len_jitter=0.0, seed=404,
                 contig_prefix="ecoli"),
         {"thresholds": [0.25]}, {}),
        ("amplicon_deep",
         SimSpec(n_contigs=1, contig_len=400, n_reads=n(100000),
                 read_len=80, ins_read_rate=0.3, del_read_rate=0.2,
                 seed=303, contig_prefix="amplicon"),
         {"thresholds": [0.25], "min_depth": 10},
         {"pallas": {"ins_kernel": "pallas"}}),
    ]


def run_once(backend, path, cfg, binary):
    from sam2consensus_tpu.io.fasta import render_file
    from sam2consensus_tpu.io.sam import ReadStream, opener, read_header

    handle = opener(path, binary=binary)
    contigs, _n, first = read_header(handle)
    t0 = time.perf_counter()
    res = backend.run(contigs, ReadStream(handle, first), cfg)
    elapsed = time.perf_counter() - t0
    handle.close()
    rendered = {n: render_file(r, 0) for n, r in res.fastas.items()}
    return res.stats, elapsed, rendered


def phase_split(stats):
    return {k: stats.extra[k]
            for k in ("accumulate_sec", "vote_sec", "insertions_sec",
                      "render_sec") if k in stats.extra}


def bench_config(name, spec, cfg_kwargs, jax_variants, tmp):
    from sam2consensus_tpu.backends.cpu import CpuBackend
    from sam2consensus_tpu.backends.jax_backend import JaxBackend
    from sam2consensus_tpu.config import RunConfig
    from sam2consensus_tpu.utils.simulate import simulate

    t0 = time.perf_counter()
    text = simulate(spec)
    path = os.path.join(tmp, f"{name}.sam")
    with open(path, "w") as fh:
        fh.write(text)
    log(f"[{name}] simulated {spec.n_reads} reads in "
        f"{time.perf_counter() - t0:.1f}s")
    del text

    cfg = RunConfig(prefix="bench", **{"shards": 1, **cfg_kwargs})
    cpu_stats, cpu_time, cpu_out = run_once(CpuBackend(), path, cfg,
                                            binary=False)
    log(f"[{name}] cpu oracle: {cpu_time:.2f}s "
        f"({cpu_stats.consensus_bases / cpu_time:,.0f} bases/s)")

    rows = []
    variants = {"": {}}
    variants.update(jax_variants)
    for vname, overrides in variants.items():
        vcfg = RunConfig(prefix="bench", **{"shards": 1, **cfg_kwargs,
                                            **overrides})
        backend = JaxBackend()
        # warm-up pays the jit compiles for this genome length / buckets
        _s, _t, _o = run_once(backend, path, vcfg, binary=True)
        jax_stats, jax_time, jax_out = run_once(backend, path, vcfg,
                                                binary=True)
        identical = jax_out == cpu_out
        row_name = name if not vname else f"{name}+{vname}"
        bases = jax_stats.consensus_bases
        row = {
            "config": row_name,
            "reads": jax_stats.reads_mapped,
            "aligned_bases": jax_stats.aligned_bases,
            "consensus_bases": bases,
            "cpu_sec": round(cpu_time, 3),
            "jax_sec": round(jax_time, 3),
            "bases_per_sec": round(bases / jax_time, 1),
            "vs_baseline": round(cpu_time / jax_time, 3),
            "identical": identical,
            "phases": phase_split(jax_stats),
            "pileup": jax_stats.extra.get("pileup", {}),
        }
        if "insertion_kernel" in jax_stats.extra:
            row["insertion_kernel"] = jax_stats.extra["insertion_kernel"]
        rows.append(row)
        log(f"[{row_name}] jax: {jax_time:.2f}s "
            f"({row['bases_per_sec']:,.0f} bases/s, "
            f"{row['vs_baseline']}x cpu, identical={identical}) "
            f"phases={row['phases']}")
        if not identical:
            log(f"[{row_name}] BYTE MISMATCH — row marked identical=false")
    return rows


def main():
    result = {
        "metric": "consensus_bases_per_sec",
        "value": 0.0,
        "unit": "bases/sec",
        "vs_baseline": 0.0,
    }
    try:
        ok, platform, n_dev, probe_err = probe_accelerator()
        if not ok:
            # fall back to the XLA CPU backend so the bench still produces
            # a complete (if unflattering) result set
            os.environ["JAX_PLATFORMS"] = "cpu"
            result["device"] = "cpu-fallback"
            result["tpu_unavailable"] = True
            result["probe_error"] = probe_err
            log("[probe] accelerator unavailable; falling back to "
                "JAX_PLATFORMS=cpu")
        else:
            result["device"] = platform
            result["n_devices"] = n_dev
        # re-assert JAX_PLATFORMS over any sitecustomize jax.config override
        from sam2consensus_tpu.utils.platform import pin_platform_from_env
        pin_platform_from_env()

        only = [s for s in os.environ.get("BENCH_CONFIGS", "").split(",")
                if s]
        rows = []
        with tempfile.TemporaryDirectory() as tmp:
            for name, spec, cfg_kwargs, variants in build_configs(
                    n_dev if ok else 1):
                if only and name not in only:
                    continue
                try:
                    rows.extend(bench_config(name, spec, cfg_kwargs,
                                             variants, tmp))
                except Exception as exc:  # keep earlier rows on any failure
                    log(f"[{name}] FAILED: {type(exc).__name__}: {exc}")
                    rows.append({"config": name, "error": repr(exc)})
        result["configs"] = rows

        head = next((r for r in rows
                     if r.get("config") == "headline" and "error" not in r),
                    None)
        scored = [r for r in rows
                  if "error" not in r and r.get("identical")]
        if head is not None and head.get("identical"):
            result["value"] = head["bases_per_sec"]
            result["vs_baseline"] = head["vs_baseline"]
        elif scored:  # headline missing: fall back to the first clean row
            result["value"] = scored[0]["bases_per_sec"]
            result["vs_baseline"] = scored[0]["vs_baseline"]
            result["headline_fallback"] = scored[0]["config"]
        if any(not r.get("identical", True) for r in rows):
            result["byte_mismatch"] = True
    except Exception as exc:
        result["error"] = repr(exc)
        log(f"[bench] FATAL: {exc!r}")
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
