#!/usr/bin/env python3
"""Benchmark: consensus bases/sec, jax backend vs the CPU golden baseline.

Prints ONE JSON line:
  {"metric": "consensus_bases_per_sec", "value": N, "unit": "bases/sec",
   "vs_baseline": N}

``value`` is the end-to-end jax-backend throughput (SAM text -> FASTA
records, warm compile) on this machine's default JAX device (the TPU chip
under the driver); ``vs_baseline`` is the speedup over the CPU golden
backend on the identical workload (BASELINE.md's primary metric).  The run
also asserts FASTA byte-identity between the two backends — a benchmark
that produced wrong bytes would be meaningless.

Workload knobs via env: BENCH_READS (default 200000), BENCH_CONTIGS (100),
BENCH_READ_LEN (100), BENCH_CONTIG_LEN (2000).
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from sam2consensus_tpu.utils.platform import pin_platform_from_env  # noqa: E402
pin_platform_from_env()

from sam2consensus_tpu.backends.cpu import CpuBackend          # noqa: E402
from sam2consensus_tpu.backends.jax_backend import JaxBackend  # noqa: E402
from sam2consensus_tpu.config import RunConfig                 # noqa: E402
from sam2consensus_tpu.io.fasta import render_file             # noqa: E402
from sam2consensus_tpu.io.sam import ReadStream, opener, read_header  # noqa: E402
from sam2consensus_tpu.utils.simulate import SimSpec, simulate  # noqa: E402


def run_once(backend, path, cfg, binary):
    handle = opener(path, binary=binary)
    contigs, _n, first = read_header(handle)
    t0 = time.perf_counter()
    res = backend.run(contigs, ReadStream(handle, first), cfg)
    elapsed = time.perf_counter() - t0
    handle.close()
    rendered = {n: render_file(r, 0) for n, r in res.fastas.items()}
    return res.stats, elapsed, rendered


def main():
    spec = SimSpec(
        n_contigs=int(os.environ.get("BENCH_CONTIGS", "100")),
        contig_len=int(os.environ.get("BENCH_CONTIG_LEN", "2000")),
        n_reads=int(os.environ.get("BENCH_READS", "200000")),
        read_len=int(os.environ.get("BENCH_READ_LEN", "100")),
        ins_read_rate=0.05, del_read_rate=0.05, seed=42)
    text = simulate(spec)
    cfg = RunConfig(prefix="bench", thresholds=[0.25])

    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "bench.sam")
        with open(path, "w") as fh:
            fh.write(text)
        del text

        cpu_stats, cpu_time, cpu_out = run_once(CpuBackend(), path, cfg,
                                                binary=False)

        jax_backend = JaxBackend()
        # warm-up: pays jit compiles for this genome length / chunk buckets
        _stats, _t, _out = run_once(jax_backend, path, cfg, binary=True)
        jax_stats, jax_time, jax_out = run_once(jax_backend, path, cfg,
                                                binary=True)

    assert jax_out == cpu_out, "BENCH INVALID: backends disagree byte-wise"
    bases = jax_stats.consensus_bases
    value = bases / jax_time
    baseline = bases / cpu_time
    print(json.dumps({
        "metric": "consensus_bases_per_sec",
        "value": round(value, 1),
        "unit": "bases/sec",
        "vs_baseline": round(value / baseline, 3),
    }))


if __name__ == "__main__":
    main()
