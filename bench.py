#!/usr/bin/env python3
"""Benchmark: consensus bases/sec, jax backend vs the CPU golden baseline.

Prints ONE JSON line to stdout:
  {"metric": "consensus_bases_per_sec", "value": N, "unit": "bases/sec",
   "vs_baseline": N, "device": "...", "configs": [...], ...}

``value`` is the end-to-end jax-backend throughput (SAM text -> FASTA
records, warm compile) on the north-star workload (1M reads / 500 contigs —
the row BASELINE.md defines the >=100x target on); ``vs_baseline`` is the
speedup over the CPU golden backend on that identical workload (BASELINE.md's
primary metric).  The smaller ``headline`` row remains in ``configs`` as the
round-over-round comparable workload.  ``configs`` carries one row per BASELINE.md scenario
(phiX, multi-threshold, target capture, E. coli scale, insertion-heavy
amplicon — plus the Pallas-kernel variant of the amplicon) with per-phase
timings.  Every row asserts FASTA byte-identity between the two backends —
a benchmark that produced wrong bytes would be meaningless.

Robustness (round 1 ended with rc=1 and no number because jax.devices()
crashed in-process after the CPU baseline had already run):

* the accelerator is probed in a SUBPROCESS with a timeout and retries, so
  a hung/unavailable tunnel cannot hang or crash the bench itself;
* if the accelerator never comes up, the bench falls back to the XLA CPU
  backend, still reports the full result set, and marks the headline line
  with ``"device": "cpu-fallback"`` plus the probe's error tail;
* progress and per-config rows stream to stderr; stdout stays exactly one
  JSON line, emitted even on partial failure.

Env knobs: BENCH_SCALE (read-count multiplier, default 1.0), BENCH_CONFIGS
(comma-separated subset of config names), BENCH_READS / BENCH_CONTIGS /
BENCH_READ_LEN / BENCH_CONTIG_LEN (headline workload, defaults 200000 /
100 / 100 / 2000), BENCH_INIT_TIMEOUT (probe seconds, default 300),
BENCH_INIT_RETRIES (default 2), BENCH_SERVE_JOBS (serve-leg batch size,
default 8; 0 disables the leg), BENCH_SERVE_BATCH_JOBS (continuous-
batching leg: warm-serial vs warm-packed jobs/sec over one small-job
queue, default 16; 0 disables), BENCH_INCR_PCT (incremental-consensus
leg: +N% reads on a warm per-reference count cache vs the cold
combined job, default 10; 0 disables; BENCH_INCR_READS sizes the
base), BENCH_FLEET_JOBS / BENCH_FLEET_WORKERS (fleet queue-drain leg,
defaults 6 / 2; 0 jobs disables), BENCH_STREAM_WAVES
(streaming-session leg: the same reads absorbed live in N journaled
waves with read-until early stop vs the one-shot cold job, default
10; 0 disables), BENCH_COHORT_SAMPLES (cohort-serving leg: one
shared-reference manifest streamed in packed waves vs the
packed-stranger path, default 200; 0 disables), BENCH_FULL_OUT /
BENCH_TAG (write the
complete result object — every row, untruncated — to this path / to
BENCH_<tag>.full.json, so downstream consumers stop recovering rows
from head-truncated stdout captures).
"""

import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def probe_accelerator():
    """Try to initialize the default JAX backend in a subprocess.

    Returns (ok, platform, n_devices, diagnostics).  A subprocess probe
    cannot hang or kill the bench: a wedged tunnel hits the timeout and a
    crash stays in the child.
    """
    # healthy probes come up in seconds (2-30 s incl. first dial); 300 s
    # only matters when the tunnel is wedged, where a lower bound gets
    # the cpu-fallback bench running instead of burning the run's budget
    timeout = int(os.environ.get("BENCH_INIT_TIMEOUT", "300"))
    retries = int(os.environ.get("BENCH_INIT_RETRIES", "2"))
    here = os.path.dirname(os.path.abspath(__file__))
    # pin_platform_from_env: the environment's sitecustomize overrides
    # jax_platforms via jax.config, which silently trumps JAX_PLATFORMS —
    # without the pin, a JAX_PLATFORMS=cpu probe would still dial the
    # remote accelerator (round-1 failure mode)
    code = (f"import sys; sys.path.insert(0, {here!r}); "
            "from sam2consensus_tpu.utils.platform import "
            "pin_platform_from_env; pin_platform_from_env(); "
            "import jax; ds = jax.devices(); "
            "print('PROBE_OK', ds[0].platform, len(ds))")
    last_err = ""
    for attempt in range(1, retries + 1):
        log(f"[probe] attempt {attempt}/{retries} "
            f"(timeout {timeout}s, JAX_PLATFORMS="
            f"{os.environ.get('JAX_PLATFORMS', '<unset>')})")
        t0 = time.perf_counter()
        try:
            r = subprocess.run([sys.executable, "-c", code],
                               capture_output=True, text=True,
                               timeout=timeout)
        except subprocess.TimeoutExpired:
            last_err = f"probe timed out after {timeout}s"
            log(f"[probe] {last_err}")
            continue
        dt = time.perf_counter() - t0
        for line in r.stdout.splitlines():
            if line.startswith("PROBE_OK"):
                _tag, platform, n = line.split()
                log(f"[probe] backend up in {dt:.1f}s: "
                    f"{platform} x{n}")
                return True, platform, int(n), last_err
        last_err = (r.stderr.strip().splitlines() or ["no output"])[-1]
        log(f"[probe] failed after {dt:.1f}s (rc={r.returncode}): "
            f"{last_err}")
        if attempt < retries:
            time.sleep(min(60, 15 * attempt))
    return False, "", 0, last_err


def build_configs(n_devices: int, platform: str = ""):
    """Per-config rows pin ``shards=1`` so every row is a clean single-chip
    number (BASELINE.md's primary metric is bases/sec/chip); when more than
    one device is up, the headline also runs a ``sharded`` variant over all
    of them (shards=0) so the dp collective path gets a measured row."""
    from sam2consensus_tpu.utils.simulate import SimSpec

    scale = float(os.environ.get("BENCH_SCALE", "1.0"))

    def n(reads):
        return max(1000, int(reads * scale))

    # headline: the round-over-round comparable workload (same as round 1)
    headline_spec = SimSpec(
        n_contigs=int(os.environ.get("BENCH_CONTIGS", "100")),
        contig_len=int(os.environ.get("BENCH_CONTIG_LEN", "2000")),
        n_reads=n(int(os.environ.get("BENCH_READS", "200000"))),
        read_len=int(os.environ.get("BENCH_READ_LEN", "100")),
        ins_read_rate=0.05, del_read_rate=0.05, seed=42)

    # the north star workload the >=100x target is defined on (BASELINE.md:
    # 1M reads / 500 contigs)
    north_star_spec = SimSpec(
        n_contigs=500, contig_len=2000, n_reads=n(1_000_000), read_len=100,
        ins_read_rate=0.05, del_read_rate=0.05, seed=77,
        contig_prefix="ns")

    # long-context: >= 2^25 positions on real hardware.  The oracle
    # allocates one dict per position up front (the reference design flaw
    # sp exists to escape, /root/reference/sam2consensus.py:167) — ~12 GB
    # of dicts and 205 s measured at this scale on the 125 GB bench host,
    # so the oracle runs EXACTLY (round-4; the round-3 1/16-scale linear
    # extrapolation understated the true cost by ~1.7x — dict-allocation
    # pressure is superlinear).  Hosts without the memory can restore the
    # anchor via BENCH_WIDE_ORACLE_SHRINK.
    wide_spec = SimSpec(
        n_contigs=1, contig_len=40_000_000, n_reads=n(100_000),
        read_len=100, contig_len_jitter=0.0, seed=88, contig_prefix="chr")

    # the five BASELINE.md scenarios (bench-scaled shapes; the spec-scaled
    # originals live in utils.simulate.BASELINE_SPECS for tests), plus the
    # north-star and long-context rows.  Optional per-config key
    # "oracle_shrink": run the CPU oracle at spec scaled by 1/k.
    return [
        # (name, spec, cfg_kwargs, jax_variants, extras)
        ("headline", headline_spec, {"thresholds": [0.25]},
         {"sharded": {"shards": 0,
                      "_env": {"S2C_SYNC_ACCUMULATE": "1"}}}
         if n_devices > 1 else {}, {}),
        ("phix", SimSpec(n_contigs=1, contig_len=5386, n_reads=n(20000),
                         read_len=100, seed=101, contig_prefix="phiX"),
         {"thresholds": [0.25]}, {}, {}),
        ("phix_multithreshold",
         SimSpec(n_contigs=1, contig_len=5386, n_reads=n(20000),
                 read_len=100, seed=101, contig_prefix="phiX"),
         {"thresholds": [0.25, 0.50, 0.75]}, {}, {}),
        ("target_capture",
         SimSpec(n_contigs=350, contig_len=1200, n_reads=n(100000),
                 read_len=100, seed=202, contig_prefix="gene"),
         {"thresholds": [0.25]}, {}, {}),
        ("ecoli_scale",
         SimSpec(n_contigs=1, contig_len=4_600_000, n_reads=n(150000),
                 read_len=100, contig_len_jitter=0.0, seed=404,
                 contig_prefix="ecoli"),
         # auto picks the link-free host path here when the native lib
         # builds (the row's "pileup" field records which path actually
         # ran — host_fused vs scatter_*); the +device variant pins the
         # chip pileup AND the device tail so the chip does all the work
         # and its efficiency is a measured number (VERDICT r3 #3).  On
         # the real chip two kernel variants run: +pallas measures the
         # tile-CSR histogram kernel (the production device kernel,
         # round 5), +mxu the RETIRED one-hot matmul (kept measured so
         # the PERF.md retirement note stays evidence-backed); both are
         # chip-only — interpreted/scalar on the XLA-CPU fallback
         {"thresholds": [0.25]},
         {"device": {"pileup": "scatter",
                     "_env": {"S2C_TAIL_DEVICE": "default",
                              "S2C_SYNC_ACCUMULATE": "1"}},
          **({"pallas": {"pileup": "pallas",
                         "_env": {"S2C_TAIL_DEVICE": "default",
                                  "S2C_SYNC_ACCUMULATE": "1"}},
              "mxu": {"pileup": "mxu",
                      "_env": {"S2C_TAIL_DEVICE": "default",
                               "S2C_SYNC_ACCUMULATE": "1"}}}
             if platform == "tpu" else {})}, {}),
        ("amplicon_deep",
         SimSpec(n_contigs=1, contig_len=400, n_reads=n(100000),
                 read_len=80, ins_read_rate=0.3, del_read_rate=0.2,
                 seed=303, contig_prefix="amplicon"),
         # +device (scatter insertion) and +pallas (fused in-kernel
         # vote) both pin the chip tail, so the insertion-kernel
         # comparison is forced-device vs forced-device (VERDICT r4
         # #2's done criterion); the unforced row keeps auto's pick
         {"thresholds": [0.25], "min_depth": 10},
         {"device": {"ins_kernel": "scatter",
                     "_env": {"S2C_TAIL_DEVICE": "default",
                              "S2C_SYNC_ACCUMULATE": "1"}},
          "pallas": {"ins_kernel": "pallas",
                     "_env": {"S2C_TAIL_DEVICE": "default",
                              "S2C_SYNC_ACCUMULATE": "1"}}}, {}),
        ("north_star", north_star_spec, {"thresholds": [0.25]},
         # forced-chip leg: device pileup + device tail, so the flagship
         # workload has a row where the TPU does the work even when the
         # placement model (correctly, on a slow link) routes host-side
         {"device": {"pileup": "scatter",
                     "_env": {"S2C_TAIL_DEVICE": "default",
                              "S2C_SYNC_ACCUMULATE": "1"}}}, {}),
        ("wide_genome", wide_spec, {"thresholds": [0.25]}, {},
         {"oracle_shrink":
          int(os.environ.get("BENCH_WIDE_ORACLE_SHRINK", "1"))}),
        # --- input-format legs (sam2consensus_tpu/formats) ---
        # ecoli_bam: the SAME corpus as ecoli_scale, container-converted.
        # The default row ingests BAM (block-parallel BGZF + binary
        # record decode); +gzip_sam ingests the BGZF-compressed SAM twin
        # (block-parallel inflate + native text parse) — the
        # "equivalent gzip-SAM leg" the BAM decode_sec is judged
        # against.  ONE cpu-oracle run (on the SAM text) prices both,
        # and byte-identity is asserted per row.
        ("ecoli_bam",
         SimSpec(n_contigs=1, contig_len=4_600_000, n_reads=n(150000),
                 read_len=100, contig_len_jitter=0.0, seed=404,
                 contig_prefix="ecoli"),
         {"thresholds": [0.25]},
         {"gzip_sam": {}},
         {"convert": {"": "bam", "gzip_sam": "bgzf_sam"}}),
        # longread_ont: ONT/PacBio-like dense-indel long reads (10 kb,
        # ~50 indel events/read) — the segmented slab layout + the
        # insertion table under long-CIGAR stress, ingested as BAM with
        # a +sam text-path control row
        ("longread_ont",
         SimSpec(n_contigs=2, contig_len=120_000, n_reads=n(4000),
                 read_len=10_000, n_indels=50, max_indel=8,
                 contig_len_jitter=0.0, seed=505, contig_prefix="ont"),
         {"thresholds": [0.25]},
         {"sam": {}},
         {"convert": {"": "bam", "sam": None}}),
    ]


def run_once(backend, path, cfg, binary):
    from sam2consensus_tpu.config import resolve_decode_threads
    from sam2consensus_tpu.formats import open_alignment_input
    from sam2consensus_tpu.io.fasta import render_file

    ai = open_alignment_input(path, getattr(cfg, "input_format", "auto"),
                              binary=binary,
                              threads=resolve_decode_threads(cfg))
    t0 = time.perf_counter()
    res = backend.run(ai.contigs, ai.stream, cfg)
    elapsed = time.perf_counter() - t0
    ai.close()
    rendered = {n: render_file(r, 0) for n, r in res.fastas.items()}
    return res.stats, elapsed, rendered


def phase_split(stats):
    # the keys are the compat view over the observability metrics
    # registry (observability.publish_stats_extra): one canonical
    # source for every phase second this bench reports
    return {k: stats.extra[k]
            for k in ("decode_sec", "stage_sec", "pileup_dispatch_sec",
                      "accumulate_sec", "vote_sec", "insertions_sec",
                      "render_sec")
            if k in stats.extra}


def util_fields(stats, jax_time):
    """Wire/throughput/efficiency accounting so regressions are
    attributable (VERDICT r2 #5) and chip efficiency is a number
    (VERDICT r3 #3): bytes each way, effective link rate + utilization %
    against the modeled link, pileup cell rate + % of the measured
    scatter roofline, MXU padded-lane occupancy, host decode rate."""
    u = {}
    h2d = stats.extra.get("h2d_bytes", 0)
    d2h = stats.extra.get("d2h_bytes", 0)
    u["h2d_mb"] = round(h2d / 1e6, 2)
    u["d2h_mb"] = round(d2h / 1e6, 2)
    pileup = stats.extra.get("pileup", {})
    if jax_time > 0:
        u["wire_mbps"] = round((h2d + d2h) / 1e6 / jax_time, 1)
        if h2d + d2h > 0:
            # % of the modeled link rate (self-calibrated / env / default
            # — the same constant the placement gates price with)
            from sam2consensus_tpu.backends.jax_backend import \
                _link_constants

            _rt, link_bps = _link_constants()
            u["modeled_link_mbps"] = round(link_bps / 1e6, 1)
            # can exceed 100%: the model's probed rate bills small
            # (1 MB) serial transfers, while pipelined bulk staging
            # sustains more (round-4 probe: 10-15 MB/s probed vs
            # ~32 MB/s sustained) — the gap is the probe's honest
            # conservatism, shown here so the % is interpretable
            u["link_util_pct"] = round(
                100.0 * (h2d + d2h) / jax_time / link_bps, 1)
    # R6 wire + pipeline story: what the row codec saved on the link and
    # how much of the staging transfer work ran under accumulate
    wire_info = stats.extra.get("wire")
    if isinstance(wire_info, dict) and wire_info.get("chosen"):
        u["wire_codec"] = wire_info["chosen"]
    raw_b = stats.extra.get("wire/raw_bytes", 0)
    wire_b = stats.extra.get("wire/bytes", 0)
    if raw_b and wire_b:
        u["wire_ratio"] = round(raw_b / wire_b, 2)
    ov = stats.extra.get("pipeline/overlap_sec")
    if ov is not None:
        u["overlap_sec"] = round(ov, 4)
        # denominator: the stager's own stage seconds (encode+transfer
        # work only — the phase/stage_sec counter matches it now that
        # slot backpressure is clocked outside the stage span)
        pinfo = stats.extra.get("pipeline")
        ssec = (pinfo or {}).get("stage_sec") \
            or stats.extra.get("stage_sec", 0)
        if ssec:
            u["overlap_pct"] = round(100.0 * ov / ssec, 1)
    ps = stats.extra.get("pileup_dispatch_sec", 0)
    device_pileup = any(k.startswith(("scatter_", "mxu_", "pallas_",
                                      "window_", "routed_", "dpsp_"))
                        for k in pileup)
    if (ps > 0.005 and device_pileup
            and stats.extra.get("accumulate_synced")):
        # bill the device cell rate against the accumulate window, not
        # the dispatch time: dispatches are async, so the rate is only
        # attributable when the window ended at the explicit device
        # barrier (accumulate_synced, set under S2C_SYNC_ACCUMULATE=1 —
        # the bench exports it for every device-pileup variant); cells/s
        # is then the chip's real aggregate rate (decode overlaps via
        # the prefetcher; the device is the window's bottleneck)
        acc_sec = stats.extra.get("accumulate_sec", 0) or ps
        mcells = stats.aligned_bases / acc_sec / 1e6
        u["pileup_mcells_per_s"] = round(mcells, 1)
        if any(k.startswith("scatter_") for k in pileup):
            # % of the measured on-chip scatter roofline (PERF.md §1:
            # ~53 M cells/s data-resident — reconfirmed by the round-4
            # probe's 159 ms resident slab; override for other chips).
            # Only meaningful when the device is a real accelerator —
            # the cpu-fallback bench would report nonsense percentages
            import jax

            if jax.default_backend() != "cpu":
                roof = float(os.environ.get(
                    "S2C_BENCH_SCATTER_ROOFLINE_MCELLS", "53"))
                u["scatter_roofline_pct"] = round(
                    100.0 * mcells / roof, 1)
    if "mxu_blowup" in pileup:
        # 100% = every MXU lane carried a real row; padding is the loss
        u["mxu_occupancy_pct"] = round(100.0 / pileup["mxu_blowup"], 1)
    ds = stats.extra.get("decode_sec", 0)
    if ds > 0:
        u["decode_mbases_per_s"] = round(
            stats.aligned_bases / ds / 1e6, 1)
    # memory plane (observability/memplane.py): per-family peak bytes
    # + process/device watermarks, so every bench row answers "what
    # did this config pin" and the regression gate can band it
    mem = {}
    for k, v in stats.extra.items():
        if k.startswith("mem/peak_bytes/"):
            mem[k[len("mem/peak_bytes/"):] + "_peak_mb"] = \
                round(v / 1e6, 2)
    ptb = stats.extra.get("mem/peak_tracked_bytes")
    if ptb:
        mem["tracked_peak_mb"] = round(ptb / 1e6, 2)
    if stats.extra.get("peak_rss_mb"):
        mem["peak_rss_mb"] = stats.extra["peak_rss_mb"]
    if stats.extra.get("mem/device_peak_bytes"):
        mem["device_peak_mb"] = round(
            stats.extra["mem/device_peak_bytes"] / 1e6, 2)
    if mem:
        u["mem"] = mem
    # placement-gate decisions, from the observability registry's compat
    # view (backends/jax_backend._tail_cpu_wins records the model's
    # verdict with its cpu_sec/chip_sec/link inputs; the pileup gauge
    # records host vs device vs sharded): a mis-routed row is
    # diagnosable from the bench JSON alone
    tail = stats.extra.get("tail_dispatch")
    if tail:
        u["dispatch"] = tail
    pp = stats.extra.get("pileup_path")
    if pp:
        u["pileup_path"] = pp
    return u


def _write_sim(spec, name, tmp):
    from sam2consensus_tpu.utils.simulate import simulate

    t0 = time.perf_counter()
    text = simulate(spec)
    path = os.path.join(tmp, f"{name}.sam")
    with open(path, "w") as fh:
        fh.write(text)
    log(f"[{name}] simulated {spec.n_reads} reads in "
        f"{time.perf_counter() - t0:.1f}s")
    return path


def _convert_input(sam_path, kind, tmp, name):
    """Container-convert a simulated SAM for a format bench leg:
    ``bam`` (binary records in BGZF) or ``bgzf_sam`` (the same text,
    BGZF-framed — what htslib writes as .sam.gz).  None/"" = the SAM
    itself."""
    if not kind:
        return sam_path
    t0 = time.perf_counter()
    with open(sam_path, "r") as fh:
        text = fh.read()
    if kind == "bam":
        from sam2consensus_tpu.formats.bam import sam_text_to_bam

        out = os.path.join(tmp, f"{name}.bam")
        sam_text_to_bam(text, out)
    elif kind == "bgzf_sam":
        from sam2consensus_tpu.formats.bgzf import write_bgzf

        out = os.path.join(tmp, f"{name}.sam.gz")
        write_bgzf(text.encode("ascii"), out)
    else:
        raise ValueError(f"unknown conversion {kind!r}")
    log(f"[{name}] converted to {kind} "
        f"({os.path.getsize(out) / 1e6:.1f} MB) in "
        f"{time.perf_counter() - t0:.1f}s")
    return out


def _jax_row(name, path, cfg_kwargs, overrides, cpu_time, cpu_out):
    """Warm + timed jax run; returns the result row (identical vs cpu_out
    unless cpu_out is None).  ``overrides`` may carry a ``"_env"`` dict
    applied around the runs — forced-placement variants (e.g.
    S2C_TAIL_DEVICE=default) use it so the chip path gets first-class
    measured rows even where auto would route host-side (VERDICT r3 #3)."""
    from sam2consensus_tpu.backends.jax_backend import JaxBackend
    from sam2consensus_tpu.config import RunConfig

    overrides = dict(overrides)
    env = overrides.pop("_env", {})
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        # decode_threads 0 = auto: engages the parallel fused decode and
        # the threaded native vote on multi-core hosts (no-op on 1 core)
        vcfg = RunConfig(prefix="bench",
                         **{"shards": 1, "decode_threads": 0,
                            **cfg_kwargs, **overrides})
        backend = JaxBackend()
        # warm-up pays the jit compiles for this genome length / buckets
        _s, _t, _o = run_once(backend, path, vcfg, binary=True)
        jax_stats, jax_time, jax_out = run_once(backend, path, vcfg,
                                                binary=True)
        if jax_time < 10.0:
            # same noise argument as the oracle side: best of two, plus a
            # third rep for sub-second rows — their ratio swings ~1.5x on
            # one-core host noise and the headline metric rides one
            for _ in range(2 if jax_time < 1.0 else 1):
                s3, t3, o3 = run_once(backend, path, vcfg, binary=True)
                if t3 < jax_time:
                    jax_stats, jax_time, jax_out = s3, t3, o3
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    bases = jax_stats.consensus_bases
    row = {
        "config": name,
        "reads": jax_stats.reads_mapped,
        "aligned_bases": jax_stats.aligned_bases,
        "consensus_bases": bases,
        "cpu_sec": round(cpu_time, 3),
        "jax_sec": round(jax_time, 3),
        "bases_per_sec": round(bases / jax_time, 1),
        "vs_baseline": round(cpu_time / jax_time, 3),
        "phases": phase_split(jax_stats),
        "util": util_fields(jax_stats, jax_time),
        "pileup": jax_stats.extra.get("pileup", {}),
    }
    # top-level so tools/regress_check.py bands it per config like
    # jax_sec (process peak RSS is monotone within one bench process;
    # the per-config isolation leg is tools/mem_watermark.py, which
    # runs each config in its own subprocess)
    if jax_stats.extra.get("peak_rss_mb"):
        row["peak_rss_mb"] = jax_stats.extra["peak_rss_mb"]
    if cpu_out is not None:
        row["identical"] = jax_out == cpu_out
    if "insertion_kernel" in jax_stats.extra:
        row["insertion_kernel"] = jax_stats.extra["insertion_kernel"]
    # provenance: the run manifest's compact summary (git state, env
    # overrides, link-constant provenance, every model decision with
    # its prediction/measured/residual/drift) rides in the committed
    # artifact, so the number is traceable to the constants that
    # produced it.  The manifest is from the LAST rep — decisions and
    # constants are rep-invariant (same config, same process).
    from sam2consensus_tpu import observability
    from sam2consensus_tpu.observability import manifest as _manifest

    man = observability.last_manifest()
    if man is not None:
        row["manifest"] = _manifest.summarize(man)
        if man.get("drift_events"):
            row["drift_events"] = man["drift_events"]
            log(f"[{name}] DRIFT: {man['drift_events']} model "
                f"prediction(s) fell outside the residual band — see "
                f"row manifest")
    log(f"[{name}] jax: {jax_time:.2f}s "
        f"({row['bases_per_sec']:,.0f} bases/s, "
        f"{row['vs_baseline']}x cpu, "
        f"identical={row.get('identical', 'n/a')}) "
        f"phases={row['phases']} util={row['util']}")
    if row.get("identical") is False:
        log(f"[{name}] BYTE MISMATCH — row marked identical=false")
    return row


def bench_config(name, spec, cfg_kwargs, jax_variants, tmp, extras=None):
    from dataclasses import replace

    from sam2consensus_tpu.backends.cpu import CpuBackend
    from sam2consensus_tpu.config import RunConfig

    extras = extras or {}
    shrink = int(extras.get("oracle_shrink", 1))
    cfg = RunConfig(prefix="bench", **{"shards": 1, **cfg_kwargs})

    if shrink > 1:
        # oracle anchor at 1/shrink scale: the oracle's per-position dict
        # allocation cannot survive the full genome (that reference design
        # flaw is this config's raison d'etre); both its accumulate
        # (∝ reads) and vote (∝ positions) phases scale linearly, so the
        # full-size baseline is cpu_anchor * shrink, marked estimated.
        anchor = replace(spec, contig_len=spec.contig_len // shrink,
                         n_reads=max(1000, spec.n_reads // shrink))
        apath = _write_sim(anchor, f"{name}_anchor", tmp)
        cpu_stats, cpu_anchor, cpu_out = run_once(CpuBackend(), apath, cfg,
                                                  binary=False)
        log(f"[{name}] cpu oracle anchor (1/{shrink} scale): "
            f"{cpu_anchor:.2f}s")
        anchor_row = _jax_row(f"{name}_anchor", apath, cfg_kwargs, {},
                              cpu_anchor, cpu_out)
        path = _write_sim(spec, name, tmp)
        row = _jax_row(name, path, cfg_kwargs, {}, cpu_anchor * shrink,
                       None)
        row["cpu_sec_estimated"] = True
        row["oracle_anchor"] = {
            "shrink": shrink, "cpu_sec": round(cpu_anchor, 3),
            "identical": anchor_row.get("identical")}
        return [anchor_row, row]

    path = _write_sim(spec, name, tmp)
    convert = extras.get("convert")
    cpu_stats, cpu_time, cpu_out = run_once(CpuBackend(), path, cfg,
                                            binary=False)
    if cpu_time < 60.0:
        # the one-core host's absolute speed swings ~2x run to run
        # (page cache, allocator warmup, background probes), which is
        # most of the row-to-row ratio noise — take the best of two
        # whenever the re-run is affordable (covers every config except
        # the ~200 s wide-genome oracle)
        _s2, t2, _o2 = run_once(CpuBackend(), path, cfg, binary=False)
        cpu_time = min(cpu_time, t2)
    log(f"[{name}] cpu oracle: {cpu_time:.2f}s "
        f"({cpu_stats.consensus_bases / cpu_time:,.0f} bases/s)")

    rows = []
    variants = {"": {}}
    variants.update(jax_variants)
    for vname, overrides in variants.items():
        row_name = name if not vname else f"{name}+{vname}"
        # format legs: each variant may ingest a container-converted
        # twin of the oracle's SAM (the oracle always reads the text —
        # the golden-path discipline for every new format)
        vpath = path
        if convert is not None:
            vpath = _convert_input(path, convert.get(vname), tmp,
                                   row_name.replace("+", "_"))
        rows.append(_jax_row(row_name, vpath, cfg_kwargs, overrides,
                             cpu_time, cpu_out))
    return rows


def serve_leg(n_jobs):
    """The warm-serving row (PR-5 tentpole): a batch of small jobs
    through one persistent ServeRunner vs one cold CLI process per job
    (sam2consensus_tpu/serve/benchmark.py).  ``jax_sec`` is the warm
    per-job mean and ``vs_baseline`` the cold-process/warm ratio —
    directionally identical to every other row's metrics, so the
    regression gate judges the serve series with the same bands."""
    from sam2consensus_tpu.serve.benchmark import run_serve_bench

    res = run_serve_bench(n_jobs=n_jobs, log=log)
    s = res["summary"]
    row = {
        "config": "serve_warm",
        "jobs": s["n_jobs"],
        "reads_per_job": s["n_reads"],
        "jax_sec": s["warm_per_job_sec"],
        "warm_tail_sec": s["warm_tail_per_job_sec"],
        "cold_process_sec": s["cold_per_job_sec"],
        "vs_baseline": s["speedup_vs_cold"],
        "vs_baseline_kind": "cold_process",
        "identical": s["identical"],
        "serve": {
            "overlap_sec": s["overlap_sec_total"],
            "jit_hits": sum(r.get("jit_hit", 0) for r in res["rows"]
                            if r.get("mode") == "warm"),
            "jit_misses": sum(r.get("jit_miss", 0) for r in res["rows"]
                              if r.get("mode") == "warm"),
            "jit_cache_dir": s["jit_cache_dir"],
            # the warm side ran with the telemetry plane on; its
            # exposition format-lint verdict rides the gated artifact
            "telemetry": s.get("telemetry"),
            # what the capacity plane learned about this host during
            # the warm run (per-rate mean/n/confidence)
            "ratecard": s.get("ratecard"),
        },
    }
    log(f"[serve_warm] cold {s['cold_per_job_sec']}s/job vs warm "
        f"{s['warm_per_job_sec']}s/job = {s['speedup_vs_cold']}x, "
        f"identical={s['identical']}")
    return row


def serve_batch_leg(n_jobs):
    """The continuous-batching row (PR-11 tentpole): the same small-job
    queue through one warm runner serial vs packed
    (sam2consensus_tpu/serve/scheduler.py).  ``jax_sec`` is the packed
    per-job min and ``vs_baseline`` the warm-serial/warm-packed
    jobs-per-sec ratio — directionally identical to every other row's
    metrics, so the regression gate judges the batching series with
    the same bands."""
    from sam2consensus_tpu.serve.benchmark import run_serve_batch_bench

    res = run_serve_batch_bench(n_jobs=n_jobs, log=log)
    s = res["summary"]
    row = {
        "config": "serve_batch",
        "jobs": s["n_jobs"],
        "reads_per_job": s["n_reads"],
        "jax_sec": round(s["warm_packed_min_sec"] / s["n_jobs"], 4),
        "warm_serial_per_job_sec": round(
            s["warm_serial_min_sec"] / s["n_jobs"], 4),
        "vs_baseline": s["packed_vs_serial"],
        "vs_baseline_kind": "warm_serial",
        "identical": s["identical"],
        "serve_batch": {
            "packed_jobs_per_sec": s["warm_packed_jobs_per_sec"],
            "serial_jobs_per_sec": s["warm_serial_jobs_per_sec"],
            "batch": s.get("batch"),
            "decision": s.get("decision"),
        },
    }
    log(f"[serve_batch] serial {s['warm_serial_jobs_per_sec']} jobs/s "
        f"vs packed {s['warm_packed_jobs_per_sec']} jobs/s = "
        f"{s['packed_vs_serial']}x, identical={s['identical']}")
    return row


def incremental_leg(extra_pct):
    """The incremental-consensus row (ISSUE 13 tentpole): +N% reads
    against a warm per-reference count cache vs the cold job over the
    combined input, through one warm ServeRunner
    (sam2consensus_tpu/serve/benchmark.py).  ``jax_sec`` is the warm
    delta job's min wall and ``vs_baseline`` the cold/warm ratio
    (bigger = better, like every row), so the regression gate judges
    the incremental series with the same bands.  The acceptance line
    is ``incr_cost_ratio <= 0.15``."""
    from sam2consensus_tpu.serve.benchmark import run_incremental_bench

    n_reads = int(os.environ.get("BENCH_INCR_READS", "1000000"))
    res = run_incremental_bench(n_reads=n_reads, extra_pct=extra_pct,
                                log=log)
    s = res["summary"]
    row = {
        "config": "incremental",
        "reads_base": s["n_reads"],
        "extra_pct": s["extra_pct"],
        "jax_sec": s["warm_incr_min_sec"],
        "cold_sec": s["cold_min_sec"],
        "vs_baseline": round(s["cold_min_sec"]
                             / max(1e-9, s["warm_incr_min_sec"]), 2),
        "vs_baseline_kind": "cold_combined_job",
        "incr_cost_ratio": s["incr_cost_ratio"],
        "target_ratio": s["target_ratio"],
        "identical": s["identical"],
        "count_cache": {
            "cache": s.get("cache"),
            "decision": s.get("decision"),
        },
    }
    log(f"[incremental] +{extra_pct}% reads {s['warm_incr_min_sec']}s "
        f"vs cold {s['cold_min_sec']}s = "
        f"{s['incr_cost_ratio']:.2%} of cold (target <=15%), "
        f"identical={s['identical']}")
    return row


def serve_fleet_leg(n_jobs):
    """The fleet queue-drain row (ISSUE 15 tentpole): the same
    journaled queue drained by one worker vs BENCH_FLEET_WORKERS
    work-stealing workers (sam2consensus_tpu/serve/fleet.py).
    ``jax_sec`` is the fleet per-job drain wall and ``vs_baseline``
    the one-worker/fleet drain ratio (bigger = better, like every
    row), so the regression gate judges the fleet series with the
    same bands.  The ROADMAP 2(b) >=1.8x target applies on multi-core
    rigs; the row records ``host_cores`` so a 1-core harness artifact
    reads as what it is."""
    from sam2consensus_tpu.serve.benchmark import run_fleet_bench

    n_workers = int(os.environ.get("BENCH_FLEET_WORKERS", "2"))
    res = run_fleet_bench(n_jobs=n_jobs, n_workers=n_workers, log=log)
    s = res["summary"]
    row = {
        "config": "serve_fleet",
        "jobs": s["n_jobs"],
        "reads_per_job": s["n_reads"],
        "workers": s["n_workers"],
        "host_cores": s["host_cores"],
        "jax_sec": s["fleet_per_job_sec"],
        "serial_drain_sec": s["serial_drain_sec"],
        "fleet_drain_sec": s["fleet_drain_sec"],
        "vs_baseline": s["drain_speedup"],
        "vs_baseline_kind": "one_worker_drain",
        "identical": s["identical"],
        "fleet": {
            "lost": s["lost"],
            "duplicated": s["duplicated"],
            "lease_ttl_sec": s["lease_ttl_sec"],
        },
    }
    log(f"[serve_fleet] 1 worker {s['serial_drain_sec']}s vs "
        f"{s['n_workers']} workers {s['fleet_drain_sec']}s = "
        f"{s['drain_speedup']}x ({s['host_cores']} core(s)), "
        f"identical={s['identical']}")
    return row


def streaming_leg(n_waves):
    """The streaming-session row (ISSUE 17 tentpole): the same reads
    absorbed live in N journaled waves (serve/session.py) vs the
    one-shot cold batch job.  ``jax_sec`` is the session wall and
    ``vs_baseline`` the cold/stream ratio (bigger = better, like
    every row) so the regression gate judges the streaming series
    with the same bands; the row also carries the <=1.3x
    ``stream_cost_ratio`` target the ISSUE pins, the stability
    early-stop wave (the read-until verdict), and the honest
    ``stream_vs_warm`` durability bill vs a warm in-process one-shot."""
    from sam2consensus_tpu.serve.benchmark import run_streaming_bench

    res = run_streaming_bench(n_waves=n_waves, log=log)
    s = res["summary"]
    row = {
        "config": "streaming",
        "waves": s["n_waves"],
        "waves_fed": s["waves_fed"],
        "reads": s["n_reads"],
        "host_cores": s["host_cores"],
        "jax_sec": s["stream_sec"],
        "cold_sec": s["cold_sec"],
        "warm_one_shot_sec": s["warm_one_shot_sec"],
        "vs_baseline": (round(s["cold_sec"] / s["stream_sec"], 3)
                        if s["stream_sec"] else 0.0),
        "vs_baseline_kind": "one_shot_cold",
        "stream_cost_ratio": s["stream_cost_ratio"],
        "stream_vs_warm": s["stream_vs_warm"],
        "early_stop_wave": s["early_stop_wave"],
        "stable": s["stable"],
        "identical": s["digest_matches_cold"],
    }
    log(f"[streaming] {s['waves_fed']}/{s['n_waves']} wave(s) "
        f"{s['stream_sec']}s vs cold {s['cold_sec']}s = "
        f"{s['stream_cost_ratio']}x of cold (target <=1.3x), "
        f"early_stop_wave={s['early_stop_wave']}, "
        f"identical={s['digest_matches_cold']}")
    return row


def cohort_leg(n_samples):
    """The cohort-serving row (ISSUE 20 tentpole): N shared-reference
    samples listed in ONE manifest and streamed through
    serve/cohort.py in packed waves vs the PR-11 packed-STRANGER path
    over the same job class (sam2consensus_tpu/serve/benchmark.py).
    ``jax_sec`` is the cohort per-sample wall and ``vs_baseline`` the
    cohort/stranger jobs-per-sec ratio (bigger = better, like every
    row) so the regression gate judges the cohort series with the
    same bands; the row also carries the zero-replan / zero-recompile
    pins (one PanelGeometry + one compile footprint cover every wave)
    and the concordance-vs-CPU-oracle verdict."""
    from sam2consensus_tpu.serve.benchmark import run_cohort_bench

    res = run_cohort_bench(n_samples=n_samples, log=log)
    s = res["summary"]
    row = {
        "config": "cohort",
        "samples": s["n_samples"],
        "reads_per_sample": s["n_reads"],
        "waves": s["waves"],
        "jax_sec": round(s["cohort_sec"] / max(1, s["n_samples"]), 5),
        "vs_baseline": round(s["jobs_per_sec"]
                             / max(1e-9, s["stranger_jobs_per_sec"]),
                             3),
        "vs_baseline_kind": "packed_stranger",
        "identical": s["identical"],
        "cohort": {
            "jobs_per_sec": s["jobs_per_sec"],
            "stranger_jobs_per_sec": s["stranger_jobs_per_sec"],
            "occupancy_pct": s["occupancy_pct"],
            "panel_plans": s["panel_plans"],
            "panel_reuses": s["panel_reuses"],
            "replans_after_wave1": s["replans_after_wave1"],
            "new_compiles_after_wave1": s["new_compiles_after_wave1"],
            "concordance_pinned": s["concordance_pinned"],
            "residual_in_band": s["residual_in_band"],
            "ok": s["ok"],
        },
    }
    log(f"[cohort] {s['samples_ok']}/{s['n_samples']} sample(s) at "
        f"{s['jobs_per_sec']} jobs/s vs stranger "
        f"{s['stranger_jobs_per_sec']} jobs/s, "
        f"occupancy {s['occupancy_pct']}%, "
        f"replans_after_wave1={s['replans_after_wave1']}, "
        f"identical={s['identical']}")
    return row


def full_artifact_path():
    """Destination for the complete (untruncated) result object:
    BENCH_FULL_OUT wins, else BENCH_TAG -> BENCH_<tag>.full.json next
    to this script, else None (no artifact — the stdout line is all)."""
    out = os.environ.get("BENCH_FULL_OUT")
    if out:
        return out
    tag = os.environ.get("BENCH_TAG")
    if tag:
        return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            f"BENCH_{tag}.full.json")
    return None


def main():
    # the headline value/vs_baseline fields are inserted LAST so a
    # tail-truncated capture of the JSON line always retains them
    # (VERDICT r2 weak #7)
    result = {
        "metric": "consensus_bases_per_sec",
        "unit": "bases/sec",
    }
    value, vs_baseline = 0.0, 0.0
    try:
        ok, platform, n_dev, probe_err = probe_accelerator()
        if not ok:
            # fall back to the XLA CPU backend so the bench still produces
            # a complete (if unflattering) result set
            os.environ["JAX_PLATFORMS"] = "cpu"
            result["device"] = "cpu-fallback"
            result["tpu_unavailable"] = True
            result["probe_error"] = probe_err
            log("[probe] accelerator unavailable; falling back to "
                "JAX_PLATFORMS=cpu")
        else:
            result["device"] = platform
            result["n_devices"] = n_dev
        # re-assert JAX_PLATFORMS over any sitecustomize jax.config override
        from sam2consensus_tpu.utils.platform import pin_platform_from_env
        pin_platform_from_env()

        only = [s for s in os.environ.get("BENCH_CONFIGS", "").split(",")
                if s]
        rows = []
        with tempfile.TemporaryDirectory() as tmp:
            for name, spec, cfg_kwargs, variants, extras in build_configs(
                    n_dev if ok else 1, platform if ok else "cpu"):
                if only and name not in only:
                    continue
                try:
                    rows.extend(bench_config(name, spec, cfg_kwargs,
                                             variants, tmp, extras))
                except Exception as exc:  # keep earlier rows on any failure
                    log(f"[{name}] FAILED: {type(exc).__name__}: {exc}")
                    rows.append({"config": name, "error": repr(exc)})
        # warm-serving leg: rides the same rows list so the regression
        # gate sees a serve series once >=1 round of history exists
        n_serve = int(os.environ.get("BENCH_SERVE_JOBS", "8"))
        if n_serve > 0 and (not only or "serve_warm" in only):
            try:
                rows.append(serve_leg(n_serve))
            except Exception as exc:
                log(f"[serve_warm] FAILED: {type(exc).__name__}: {exc}")
                rows.append({"config": "serve_warm", "error": repr(exc)})
        # continuous-batching leg: warm-serial vs warm-packed jobs/sec
        # over one small-job queue, riding the same regression gate
        n_batch = int(os.environ.get("BENCH_SERVE_BATCH_JOBS", "16"))
        if n_batch > 0 and (not only or "serve_batch" in only):
            try:
                rows.append(serve_batch_leg(n_batch))
            except Exception as exc:
                log(f"[serve_batch] FAILED: {type(exc).__name__}: {exc}")
                rows.append({"config": "serve_batch",
                             "error": repr(exc)})
        # fleet queue-drain leg: 1 worker vs N work-stealing workers
        # over one journal (BENCH_FLEET_JOBS=0 disables)
        n_fleet = int(os.environ.get("BENCH_FLEET_JOBS", "6"))
        if n_fleet > 0 and (not only or "serve_fleet" in only):
            try:
                rows.append(serve_fleet_leg(n_fleet))
            except Exception as exc:
                log(f"[serve_fleet] FAILED: {type(exc).__name__}: "
                    f"{exc}")
                rows.append({"config": "serve_fleet",
                             "error": repr(exc)})
        # streaming-session leg: live waves + read-until early stop vs
        # the one-shot cold job (BENCH_STREAM_WAVES=0 disables)
        n_waves = int(os.environ.get("BENCH_STREAM_WAVES", "10"))
        if n_waves > 0 and (not only or "streaming" in only):
            try:
                rows.append(streaming_leg(n_waves))
            except Exception as exc:
                log(f"[streaming] FAILED: {type(exc).__name__}: {exc}")
                rows.append({"config": "streaming",
                             "error": repr(exc)})
        # cohort-serving leg: one manifest streamed in packed waves vs
        # the packed-stranger path (BENCH_COHORT_SAMPLES=0 disables)
        n_cohort = int(os.environ.get("BENCH_COHORT_SAMPLES", "200"))
        if n_cohort > 0 and (not only or "cohort" in only):
            try:
                rows.append(cohort_leg(n_cohort))
            except Exception as exc:
                log(f"[cohort] FAILED: {type(exc).__name__}: {exc}")
                rows.append({"config": "cohort", "error": repr(exc)})
        # incremental-consensus leg: +N% reads on a warm reference vs
        # the cold combined job (BENCH_INCR_PCT=0 disables)
        incr_pct = int(os.environ.get("BENCH_INCR_PCT", "10"))
        if incr_pct > 0 and (not only or "incremental" in only):
            try:
                rows.append(incremental_leg(incr_pct))
            except Exception as exc:
                log(f"[incremental] FAILED: {type(exc).__name__}: {exc}")
                rows.append({"config": "incremental",
                             "error": repr(exc)})
        result["configs"] = rows

        # the driver-recorded metric IS the north_star row: BASELINE.md
        # defines the >=100x target on the 1M-read/500-contig north-star
        # workload, so that row is THE number (VERDICT r4 weak #5 — the
        # smaller headline config is oracle-noise-bound with ~0.09 s of
        # fixed cost visible, and was under-reporting the target metric).
        # The headline row stays in configs[] as the round-over-round
        # comparable workload; fallback chain: north_star -> headline ->
        # first clean row.  Fallback pool excludes degenerate rows (a
        # 460-base amplicon "throughput" is an identity check, not a
        # headline — VERDICT r2 weak #6) and oracle-anchor rows (shrunken
        # sub-benchmarks).
        scored = [r for r in rows
                  if "error" not in r and r.get("identical")
                  and r.get("consensus_bases", 0) >= 10_000
                  and not r.get("config", "").endswith("_anchor")]

        def clean_row(name):
            return next((r for r in rows
                         if r.get("config") == name and "error" not in r
                         and r.get("identical")), None)

        ns = next((r for r in rows if r.get("config") == "north_star"
                   and "error" not in r), None)
        if ns is not None:
            result["north_star_vs_baseline"] = ns["vs_baseline"]
        head = clean_row("north_star") or clean_row("headline")
        if head is not None:
            value = head["bases_per_sec"]
            vs_baseline = head["vs_baseline"]
            result["metric_config"] = head["config"]
        elif scored:
            value = scored[0]["bases_per_sec"]
            vs_baseline = scored[0]["vs_baseline"]
            result["metric_config"] = scored[0]["config"]
        if any(not r.get("identical", True) for r in rows):
            result["byte_mismatch"] = True
    except Exception as exc:
        result["error"] = repr(exc)
        log(f"[bench] FATAL: {exc!r}")
    result["value"] = value
    result["vs_baseline"] = vs_baseline
    full_out = full_artifact_path()
    if full_out:
        # the COMPLETE result object as a committed sibling artifact:
        # driver captures keep only the tail of stdout, so the row set
        # used to be recovered by scanning truncated text
        # (observability/regress.py) — consumers now read
        # BENCH_<tag>.full.json directly when it exists
        try:
            with open(full_out, "w") as fh:
                json.dump(result, fh, indent=1)
                fh.write("\n")
            log(f"[bench] full row set written to {full_out}")
        except OSError as exc:
            log(f"[bench] could not write {full_out}: {exc}")
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    rc = main()
    # the tunneled accelerator client can abort in C++ teardown at
    # interpreter exit (dropped connection -> "terminate called ...
    # FATAL: exception not rethrown", observed exit 134) AFTER the
    # result line is printed; skip the destructors so the exit code
    # reflects the measurement, not the remote client's shutdown
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(rc)
