#!/usr/bin/env python3
"""Lint: every throughput/speedup claim in PERF.md / README.md needs evidence.

Round-5 verdict items #2/#3 were both "the number is quoted with no
committed artifact" (the 735 Mcells/s Pallas rate, the fast-link gate
flip).  This lint makes that class of drift structural: it fails when a
paragraph in PERF.md or README.md states a measured rate (``N Mcells/s``
etc.) or a speedup multiplier (``N×`` / ``Nx``) without either

* citing a committed measurement artifact IN THE SAME PARAGRAPH —
  a ``campaign/<file>`` / ``perf/<file>`` path, or one of the root
  artifacts (``BENCH_rNN.json``, ``MULTICHIP_rNN.json``,
  ``BASELINE.json``) — where the cited file must actually exist; or
* carrying an explicit ``model-only`` / ``no-artifact:`` marker, the
  loud way to say a number is modeled/projected rather than measured
  (the fastlink flip until its campaign leg lands).

Paragraph = blank-line-separated block; fenced code blocks are skipped
(command transcripts quote numbers legitimately).  Wired into tier-1 as
tests/test_perf_claims.py, so a PR cannot land an uncited claim.

Telemetry artifacts are first-class claim evidence: a cited
``.prom``/``.openmetrics`` exposition snapshot (the serve telemetry
plane's ``--telemetry-out`` / the campaign ``serve_telemetry`` leg)
must additionally PASS the OpenMetrics format lint
(``observability/telemetry.lint_openmetrics``) — a malformed
exposition is no more evidence than a missing file.  Likewise cited
flight-recorder artifacts: a ``fleet_trace*.json`` trace must pass
``observability/flight.validate`` (valid trace-event JSON, >=1
per-job track, no negative durations or orphans) and a
``fleet_trace*.jsonl`` leg result must carry a clean summary row.
Cited streaming-session soak artifacts (``session_soak*.jsonl``,
tools/session_soak.py) must likewise carry a clean summary: zero
failures, zero lost/duplicated waves, byte-identity with the one-shot
oracle, and every lease steal inside the 2x-TTL bound.

Usage: python tools/check_perf_claims.py [--repo DIR]; exit 0 clean,
1 with one violation per line otherwise.
"""

import argparse
import os
import re
import sys

DOCS = ("PERF.md", "README.md")

#: a measured-rate or speedup claim
CLAIM_RE = re.compile(
    r"\d+(?:\.\d+)?\s*(?:Mcells/s|Mbases/s|Mpos/s|Mrows/s|Mreads/s)"
    r"|\d+(?:\.\d+)?\s*×"
    r"|\b\d+(?:\.\d+)?x\b")

#: a committed-artifact citation
ARTIFACT_RE = re.compile(
    r"(?:campaign|perf)/[A-Za-z0-9_.\-]+"
    r"|BENCH_r\d+\.json|MULTICHIP_r\d+\.json|BASELINE\.json")

#: explicit "this number is modeled, not measured" markers
EXEMPT_RE = re.compile(r"model-only|no-artifact:", re.IGNORECASE)


def paragraphs(text):
    """(start_line, block) for blank-line-separated paragraphs, with
    fenced code blocks dropped."""
    out = []
    buf = []
    start = 1
    fence = False
    for i, line in enumerate(text.splitlines(), 1):
        if line.lstrip().startswith("```"):
            fence = not fence
            continue
        if fence:
            continue
        if line.strip():
            if not buf:
                start = i
            buf.append(line)
        elif buf:
            out.append((start, "\n".join(buf)))
            buf = []
    if buf:
        out.append((start, "\n".join(buf)))
    return out


def check_file(repo, name):
    path = os.path.join(repo, name)
    violations = []
    if not os.path.exists(path):
        return violations
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    for lineno, para in paragraphs(text):
        claims = CLAIM_RE.findall(para)
        if not claims:
            continue
        if EXEMPT_RE.search(para):
            continue
        cited = ARTIFACT_RE.findall(para)
        if not cited:
            violations.append(
                f"{name}:{lineno}: claim(s) {claims[:3]} cite no "
                f"campaign/ artifact (add a citation or a 'model-only' "
                f"marker)")
            continue
        for art in cited:
            art = art.rstrip(".")      # sentence-final period
            path = os.path.join(repo, art)
            if not os.path.exists(path):
                violations.append(
                    f"{name}:{lineno}: cites missing artifact {art!r}")
            elif art.endswith((".prom", ".openmetrics")):
                errs = lint_telemetry_artifact(path)
                if errs:
                    violations.append(
                        f"{name}:{lineno}: telemetry artifact {art!r} "
                        f"fails the OpenMetrics lint "
                        f"({len(errs)} error(s); first: {errs[0]})")
            elif os.path.basename(art).startswith("fleet_soak") \
                    and art.endswith(".jsonl"):
                errs = lint_fleet_soak_artifact(path)
                if errs:
                    violations.append(
                        f"{name}:{lineno}: fleet-soak artifact "
                        f"{art!r} is not valid claim evidence "
                        f"({len(errs)} error(s); first: {errs[0]})")
            elif os.path.basename(art).startswith("session_soak") \
                    and art.endswith(".jsonl"):
                errs = lint_session_soak_artifact(path)
                if errs:
                    violations.append(
                        f"{name}:{lineno}: session-soak artifact "
                        f"{art!r} is not valid claim evidence "
                        f"({len(errs)} error(s); first: {errs[0]})")
            elif os.path.basename(art).startswith("fleet_trace") \
                    and art.endswith(".jsonl"):
                errs = lint_fleet_trace_leg_artifact(path)
                if errs:
                    violations.append(
                        f"{name}:{lineno}: flight-recorder leg "
                        f"artifact {art!r} is not valid claim "
                        f"evidence ({len(errs)} error(s); "
                        f"first: {errs[0]})")
            elif os.path.basename(art).startswith("fleet_trace") \
                    and art.endswith(".json"):
                errs = lint_flight_trace_artifact(path)
                if errs:
                    violations.append(
                        f"{name}:{lineno}: flight-recorder trace "
                        f"{art!r} fails the structural lint "
                        f"({len(errs)} error(s); first: {errs[0]})")
            elif os.path.basename(art).startswith("multihost_bench") \
                    and art.endswith(".jsonl"):
                errs = lint_multihost_bench_artifact(path)
                if errs:
                    violations.append(
                        f"{name}:{lineno}: multi-host bench artifact "
                        f"{art!r} is not valid claim evidence "
                        f"({len(errs)} error(s); first: {errs[0]})")
            elif os.path.basename(art).startswith("fleet_whatif") \
                    and art.endswith(".jsonl"):
                errs = lint_fleet_whatif_artifact(path)
                if errs:
                    violations.append(
                        f"{name}:{lineno}: fleet-whatif artifact "
                        f"{art!r} is not valid claim evidence "
                        f"({len(errs)} error(s); first: {errs[0]})")
            elif os.path.basename(art).startswith("cohort") \
                    and art.endswith(".jsonl"):
                errs = lint_cohort_bench_artifact(path)
                if errs:
                    violations.append(
                        f"{name}:{lineno}: cohort bench artifact "
                        f"{art!r} is not valid claim evidence "
                        f"({len(errs)} error(s); first: {errs[0]})")
    return violations


def lint_cohort_bench_artifact(path):
    """Structural lint for a cited cohort-bench JSONL
    (tools/cohort_bench.py, the ISSUE 20 cohort-serving evidence):
    parseable rows, at least one cohort_wave row, a summary row, and
    the summary's acceptance pins intact — zero failed members, spot
    checks byte-identical to serial, the concordance digest pinned to
    the CPU oracle, zero re-plans and zero new compiles after wave 1
    (one PanelGeometry and one compile footprint cover every wave),
    no drifted cohort_wave decision, and cohort jobs/s at or above the
    packed-stranger leg.  An artifact recording any broken pin is no
    more evidence than a missing file."""
    import json

    errs = []
    try:
        with open(path, encoding="utf-8") as fh:
            lines = [ln for ln in fh if ln.strip()]
    except OSError as exc:
        return [f"unreadable: {exc}"]
    rows = []
    for i, ln in enumerate(lines, 1):
        try:
            rows.append(json.loads(ln))
        except ValueError:
            errs.append(f"line {i}: not JSON")
    if not any(r.get("mode") == "cohort_wave" for r in rows):
        errs.append("no cohort_wave rows")
    summaries = [r for r in rows if r.get("mode") == "summary"]
    if not summaries:
        errs.append("no summary row")
        return errs
    s = summaries[-1]
    if s.get("failed", 1) != 0:
        errs.append(f"summary failed={s.get('failed')}")
    if not s.get("identical", False):
        errs.append("summary identical is not true (spot-checked "
                    "members differ from serial)")
    if not s.get("concordance_pinned", False):
        errs.append("summary concordance_pinned is not true")
    if s.get("replans_after_wave1", 1) != 0:
        errs.append(f"summary replans_after_wave1="
                    f"{s.get('replans_after_wave1')}")
    if s.get("new_compiles_after_wave1", 1) != 0:
        errs.append(f"summary new_compiles_after_wave1="
                    f"{s.get('new_compiles_after_wave1')}")
    if not s.get("residual_in_band", False):
        errs.append("summary residual_in_band is not true")
    if not s.get("cohort_ge_stranger", False):
        errs.append("summary cohort_ge_stranger is not true")
    if not s.get("ok", False):
        errs.append("summary ok is not true")
    return errs


def lint_fleet_soak_artifact(path):
    """Structural lint for a cited fleet-soak JSONL (tools/
    fleet_soak.py): parseable rows, a summary row, and the summary's
    invariants intact — an artifact recording lost/duplicated jobs or
    cycle failures is no more evidence than a missing file."""
    import json

    errs = []
    try:
        with open(path, encoding="utf-8") as fh:
            lines = [ln for ln in fh if ln.strip()]
    except OSError as exc:
        return [f"unreadable: {exc}"]
    rows = []
    for i, ln in enumerate(lines, 1):
        try:
            rows.append(json.loads(ln))
        except ValueError:
            errs.append(f"line {i}: not JSON")
    summaries = [r for r in rows if r.get("mode") == "summary"]
    if not summaries:
        errs.append("no summary row")
        return errs
    s = summaries[-1]
    if s.get("lost_total", 1) != 0:
        errs.append(f"summary lost_total={s.get('lost_total')}")
    if s.get("duplicated_total", 1) != 0:
        errs.append(
            f"summary duplicated_total={s.get('duplicated_total')}")
    if not s.get("identical_all", False):
        errs.append("summary identical_all is not true")
    if s.get("failures", 1) != 0:
        errs.append(f"summary failures={s.get('failures')}")
    return errs


def lint_fleet_whatif_artifact(path):
    """Structural lint for a cited fleet-whatif JSONL
    (tools/fleet_whatif.py, the ISSUE 19 evidence-plane harness):
    parseable rows, a summary row, zero check failures, the scale-hint
    row present with its drain residual inside the recorded band, the
    burn verdicts matching the injected hang (hung tenant paged, fast
    tenant ok — replayed AND live-after-restart), a surviving card
    restart, and plane-on/off byte identity."""
    import json

    errs = []
    try:
        with open(path, encoding="utf-8") as fh:
            lines = [ln for ln in fh if ln.strip()]
    except OSError as exc:
        return [f"unreadable: {exc}"]
    rows = []
    for i, ln in enumerate(lines, 1):
        try:
            rows.append(json.loads(ln))
        except ValueError:
            errs.append(f"line {i}: not JSON")
    summaries = [r for r in rows if r.get("mode") == "summary"]
    if not summaries:
        errs.append("no summary row")
        return errs
    s = summaries[-1]
    if s.get("failures", 1) != 0:
        errs.append(f"summary failures={s.get('failures')}")
    if not s.get("identical_all", False):
        errs.append("summary identical_all is not true "
                    "(plane on/off byte identity)")
    for field, want in (("burn_verdicts", {"hung": "page",
                                           "fast": "ok"}),
                        ("burn_live_verdicts", {"hung": "page",
                                                "fast": "ok"})):
        got = s.get(field) or {}
        for tenant, state in want.items():
            if got.get(tenant) != state:
                errs.append(f"summary {field}[{tenant!r}]="
                            f"{got.get(tenant)!r}, want {state!r}")
    if s.get("card_restarts") != 1:
        errs.append(f"summary card_restarts={s.get('card_restarts')}"
                    f" (card did not survive exactly one restart)")
    hints = [r for r in rows
             if r.get("check") == "scale_hint_drain_join"]
    if not hints:
        errs.append("no scale_hint_drain_join row")
    else:
        h = hints[-1]
        resid, band = h.get("residual"), h.get("band")
        if not h.get("ok"):
            errs.append("scale_hint_drain_join row not ok")
        if not (isinstance(resid, (int, float))
                and isinstance(band, (int, float)) and band >= 1.0
                and 1.0 / band <= resid <= band):
            errs.append(f"scale-hint residual {resid!r} outside "
                        f"band {band!r}")
    return errs


def lint_session_soak_artifact(path):
    """Structural lint for a cited streaming-session soak JSONL
    (tools/session_soak.py): parseable rows, a summary row, and the
    summary's invariants intact — zero cycle failures, zero
    lost/duplicated waves, byte-identity with the one-shot batch
    oracle, and every measured lease steal inside the 2x-TTL bound.
    An artifact recording a lost wave is no more evidence than a
    missing file."""
    import json

    errs = []
    try:
        with open(path, encoding="utf-8") as fh:
            lines = [ln for ln in fh if ln.strip()]
    except OSError as exc:
        return [f"unreadable: {exc}"]
    rows = []
    for i, ln in enumerate(lines, 1):
        try:
            rows.append(json.loads(ln))
        except ValueError:
            errs.append(f"line {i}: not JSON")
    summaries = [r for r in rows if r.get("kind") == "summary"]
    if not summaries:
        errs.append("no summary row")
        return errs
    s = summaries[-1]
    if s.get("failures", 1) != 0:
        errs.append(f"summary failures={s.get('failures')}")
    if s.get("lost_total", 1) != 0:
        errs.append(f"summary lost_total={s.get('lost_total')}")
    if s.get("duplicated_total", 1) != 0:
        errs.append(
            f"summary duplicated_total={s.get('duplicated_total')}")
    if not s.get("identical_all", False):
        errs.append("summary identical_all is not true")
    bound = s.get("steal_bound_sec")
    max_steal = s.get("max_steal_sec")
    if max_steal is None:
        errs.append("summary has no measured steal latency "
                    "(no kill/wedge cycle ran?)")
    elif bound is not None and max_steal > bound:
        errs.append(f"summary max_steal_sec={max_steal} exceeds "
                    f"steal_bound_sec={bound}")
    return errs


def lint_flight_trace_artifact(path):
    """Structural lint for a cited flight-recorder trace JSON
    (``tools/fleet_trace.py --out``): valid trace-event JSON with at
    least one per-job track and zero negative-duration or orphaned
    synthetic events — ``observability/flight.validate``'s exact
    invariants, so a cited trace that Perfetto would render garbled is
    no more evidence than a missing file."""
    import json

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from sam2consensus_tpu.observability import flight

    try:
        with open(path, encoding="utf-8") as fh:
            blob = json.load(fh)
    except (OSError, ValueError) as exc:
        return [f"not valid JSON: {exc}"]
    events = blob.get("traceEvents") if isinstance(blob, dict) else blob
    if not isinstance(events, list) or not events:
        return ["no traceEvents"]
    return flight.validate(events)


def lint_fleet_trace_leg_artifact(path):
    """Structural lint for a cited flight-recorder leg JSONL
    (``tools/fleet_trace.py --leg``): parseable rows, a summary row,
    and the summary's invariants intact — zero check failures, zero
    lost/duplicated jobs, at least one per-job track assembled, and
    zero trace-validation errors."""
    import json

    errs = []
    try:
        with open(path, encoding="utf-8") as fh:
            lines = [ln for ln in fh if ln.strip()]
    except OSError as exc:
        return [f"unreadable: {exc}"]
    rows = []
    for i, ln in enumerate(lines, 1):
        try:
            rows.append(json.loads(ln))
        except ValueError:
            errs.append(f"line {i}: not JSON")
    summaries = [r for r in rows if r.get("mode") == "summary"]
    if not summaries:
        errs.append("no summary row")
        return errs
    s = summaries[-1]
    if s.get("failures", 1) != 0:
        errs.append(f"summary failures={s.get('failures')}")
    if s.get("lost_total", 1) != 0:
        errs.append(f"summary lost_total={s.get('lost_total')}")
    if s.get("duplicated_total", 1) != 0:
        errs.append(
            f"summary duplicated_total={s.get('duplicated_total')}")
    if not s.get("identical_all", False):
        errs.append("summary identical_all is not true")
    if s.get("per_job_tracks", 0) < 1:
        errs.append("summary assembled no per-job tracks")
    if s.get("validation_errors", 1) != 0:
        errs.append(
            f"summary validation_errors={s.get('validation_errors')}")
    return errs


def lint_multihost_bench_artifact(path):
    """Structural lint for a cited multi-host mesh bench JSONL
    (``tools/multihost_dryrun.py --bench``): parseable rows, a clean
    summary, and the three things a MULTICHIP citation is actually
    claiming — every row carries its shard-vs-oracle identity flag
    (true), at least one point ran genuinely multi-host (hosts > 1,
    shards > 1), and the capacity-planned admission story is recorded
    per row (a ``mesh_shards`` verdict plus a predicted-vs-measured
    residual inside the drift band)."""
    import json

    errs = []
    try:
        with open(path, encoding="utf-8") as fh:
            lines = [ln for ln in fh if ln.strip()]
    except OSError as exc:
        return [f"unreadable: {exc}"]
    rows = []
    for i, ln in enumerate(lines, 1):
        try:
            rows.append(json.loads(ln))
        except ValueError:
            errs.append(f"line {i}: not JSON")
    data = [r for r in rows if r.get("kind") == "row"]
    summaries = [r for r in rows if r.get("kind") == "summary"]
    if not summaries:
        errs.append("no summary row")
        return errs
    if not data:
        errs.append("no data rows")
        return errs
    s = summaries[-1]
    if not s.get("ok", False):
        errs.append("summary ok is not true")
    if s.get("failures", 1) != 0:
        errs.append(f"summary failures={s.get('failures')}")
    if not s.get("identical_all", False):
        errs.append("summary identical_all is not true")
    if not s.get("capacity_in_band_all", False):
        errs.append("summary capacity_in_band_all is not true")
    for i, r in enumerate(data):
        if "identical_fasta" not in r:
            errs.append(f"row {i}: no identical_fasta identity flag")
        elif not r["identical_fasta"]:
            errs.append(f"row {i}: identical_fasta is false")
        if "capacity_residual" not in r or "capacity_in_band" not in r:
            errs.append(f"row {i}: no capacity residual recorded")
        if "admission" not in r:
            errs.append(f"row {i}: no admission verdict recorded")
    if not any(r.get("hosts", 0) > 1 and r.get("shards", 0) > 1
               for r in data):
        errs.append("no row ran multi-host (hosts > 1, shards > 1)")
    if not any(str(r.get("admission", "")).startswith("admit:mesh_")
               for r in data):
        errs.append("no row carries a mesh_shards admission verdict")
    return errs


def lint_telemetry_artifact(path):
    """Format-lint a cited exposition snapshot; returns violations."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from sam2consensus_tpu.observability.telemetry import \
        lint_openmetrics

    try:
        with open(path, encoding="utf-8") as fh:
            return lint_openmetrics(fh.read())
    except OSError as exc:
        return [f"unreadable: {exc}"]


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--repo", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    args = ap.parse_args(argv)
    violations = []
    for name in DOCS:
        violations.extend(check_file(args.repo, name))
    for v in violations:
        print(v)
    if violations:
        print(f"{len(violations)} uncited perf claim(s); cite the "
              f"measurement artifact or mark the paragraph model-only",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
