#!/usr/bin/env python3
"""Serve-telemetry campaign leg: the fleet-telemetry acceptance run.

A journaled 8-job serve queue (two tenants, alternating) with ONE
``job_hang``-injected job, run TWICE — telemetry disabled, then
telemetry enabled (exposition file + SLO objectives + health snapshot
+ on-demand profiler capture armed mid-hang) — proving, in one
committed JSONL artifact:

* the exposition is updated MID-HANG on the watchdog heartbeat
  cadence (scrape rows carry growing ``s2c_serve_heartbeat_age_sec``
  values and a format-lint verdict per scrape, monotone counters
  checked across consecutive scrapes);
* per-tenant e2e/queue_wait p50/p99 summaries are present for both
  tenants;
* ``slo/violations`` burned exactly for the hung job's tenant/phase;
* a profiler capture (touch-file armed while the hang was in flight)
  was produced during the hang;
* consensus outputs are byte-identical with telemetry enabled vs
  disabled (per-job sha256 over the journal-committed output files;
  the hung job fails identically in both passes).

Usage: python tools/serve_telemetry.py [--jobs 8] [--hang-job 3]
           [--stall-timeout 3.0] [--slo e2e=1.5s]
           [--prom-out final.prom]
JSONL rows on stdout (the campaign artifact); ``--prom-out`` also
saves the final exposition text — citable claim evidence that
tools/check_perf_claims.py now format-lints.
"""

import argparse
import hashlib
import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def simulate_inputs(tmp, n_jobs):
    from sam2consensus_tpu.utils.simulate import SimSpec, simulate

    paths = []
    for k in range(n_jobs):
        spec = SimSpec(n_contigs=1, contig_len=3000, n_reads=1200,
                       read_len=100, contig_len_jitter=0.0,
                       seed=7000 + k, contig_prefix="teleref")
        path = os.path.join(tmp, f"tele_job{k}.sam")
        with open(path, "w") as fh:
            fh.write(simulate(spec))
        paths.append(path)
    return paths


def build_specs(paths, hang_job, outfolder):
    from sam2consensus_tpu.config import RunConfig, default_prefix
    from sam2consensus_tpu.serve import JobSpec

    specs = []
    for k, p in enumerate(paths):
        cfg = RunConfig(backend="jax", pileup="scatter", shards=1,
                        outfolder=outfolder, prefix=default_prefix(p),
                        fault_inject="job_hang:timeout:0:1"
                        if k == hang_job else "")
        specs.append(JobSpec(filename=p, config=cfg,
                             job_id=f"tele{k}",
                             tenant="tenant_a" if k % 2 == 0
                             else "tenant_b"))
    return specs


def out_digests(outfolder):
    out = {}
    for name in sorted(os.listdir(outfolder)):
        with open(os.path.join(outfolder, name), "rb") as fh:
            out[name] = "sha256:" + hashlib.sha256(fh.read()).hexdigest()
    return out


def run_pass(paths, tmp, tag, hang_job, stall_timeout, slo, telemetry,
             emit):
    """One 8-job journaled pass; returns (results, digests, runner
    diagnostics).  ``telemetry=False`` is the byte-identity control."""
    from sam2consensus_tpu.serve import ServeRunner

    outfolder = os.path.join(tmp, f"out_{tag}")
    os.makedirs(outfolder, exist_ok=True)
    specs = build_specs(paths, hang_job, outfolder + "/")
    kw = dict(prewarm="off", persistent_cache=False,
              journal_dir=os.path.join(tmp, f"journal_{tag}"),
              stall_timeout=stall_timeout)
    tele_path = health_path = None
    if telemetry:
        tele_path = os.path.join(tmp, "metrics.prom")
        health_path = os.path.join(tmp, "health.json")
        kw.update(telemetry_out=tele_path, health_out=health_path,
                  telemetry_interval=0.15, slo=slo)
    runner = ServeRunner(**kw)

    scrapes = []
    stop = threading.Event()

    def watcher():
        """Poll health until the hung job is in flight, then arm a
        profiler capture and take mid-hang exposition scrapes."""
        from sam2consensus_tpu.observability.telemetry import \
            lint_openmetrics

        hung_id = f"tele{hang_job}"
        prev_text = None
        armed = False
        while not stop.is_set():
            try:
                with open(health_path, encoding="utf-8") as fh:
                    health = json.load(fh)
            except (OSError, ValueError):
                time.sleep(0.05)
                continue
            if health.get("in_flight") == hung_id:
                if not armed:
                    # arm the on-demand capture WHILE the hang hangs
                    open(runner.profiler.touch_path, "w").close()
                    armed = True
                try:
                    with open(tele_path, encoding="utf-8") as fh:
                        text = fh.read()
                except OSError:
                    text = None
                if text:
                    errs = lint_openmetrics(text, prev=prev_text)
                    hb = None
                    for line in text.splitlines():
                        if line.startswith(
                                "s2c_serve_heartbeat_age_sec "):
                            hb = float(line.split()[-1])
                    scrapes.append({
                        "kind": "scrape", "during_hang": True,
                        "in_flight": health.get("in_flight"),
                        "heartbeat_age_sec": hb,
                        "health_heartbeat_age_sec":
                            health.get("last_heartbeat_age_sec"),
                        "lint_errors": len(errs),
                        "lint_first": errs[:2],
                    })
                    prev_text = text
            time.sleep(0.12)

    wt = None
    if telemetry:
        wt = threading.Thread(target=watcher, daemon=True)
        wt.start()
    t0 = time.perf_counter()
    results = runner.submit_jobs(specs)
    wall = time.perf_counter() - t0
    stop.set()
    if wt is not None:
        wt.join(timeout=5)
    diag = {
        "wall_sec": round(wall, 3),
        "violations": int(runner.registry.value("slo/violations")),
        "burn_by_tenant": dict(runner.admission.slo_burn_by_tenant),
        "profile_captures": runner.profiler.captures,
        "profile_path": runner.profiler.last_path,
        "final_exposition": runner.render_telemetry()
        if telemetry else None,
        "telemetry_write_failed": int(
            runner.registry.value("telemetry/write_failed")),
    }
    runner.close()
    for s in scrapes:
        emit(s)
    return results, out_digests(outfolder), diag


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--jobs", type=int, default=8)
    ap.add_argument("--hang-job", type=int, default=3)
    ap.add_argument("--stall-timeout", type=float, default=3.0)
    ap.add_argument("--slo", default="e2e=1.5s",
                    help="objectives for the telemetry pass (the hung "
                         "job's e2e >= --stall-timeout must breach; "
                         "warm jobs must not)")
    ap.add_argument("--prom-out", default=None,
                    help="also save the final exposition text here")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["S2C_JIT_CACHE"] = ""
    os.environ["S2C_FAULT_HANG_S"] = "600"

    def emit(row):
        print(json.dumps(row), flush=True)

    import tempfile

    from sam2consensus_tpu.observability.telemetry import (
        lint_openmetrics, parse_openmetrics)

    with tempfile.TemporaryDirectory() as tmp:
        paths = simulate_inputs(tmp, args.jobs)
        base_res, base_dig, _ = run_pass(
            paths, tmp, "off", args.hang_job, args.stall_timeout,
            None, False, emit)
        tele_res, tele_dig, diag = run_pass(
            paths, tmp, "on", args.hang_job, args.stall_timeout,
            args.slo, True, emit)

        for k, (b, t) in enumerate(zip(base_res, tele_res)):
            emit({"kind": "job", "job": k,
                  "tenant": "tenant_a" if k % 2 == 0 else "tenant_b",
                  "hang_injected": k == args.hang_job,
                  "ok_off": b.ok, "ok_on": t.ok,
                  "elapsed_off": round(b.elapsed_sec, 3),
                  "elapsed_on": round(t.elapsed_sec, 3),
                  "error_on": t.error})

        text = diag["final_exposition"] or ""
        final_lint = lint_openmetrics(text)
        samples = parse_openmetrics(text)

        def q(tenant, phase, quantile):
            for s in samples:
                if (s["name"] == "s2c_slo_phase_seconds"
                        and s["labels"].get("tenant") == tenant
                        and s["labels"].get("phase") == phase
                        and s["labels"].get("quantile") == quantile):
                    return s["value"]
            return None

        hang_tenant = "tenant_a" if args.hang_job % 2 == 0 \
            else "tenant_b"
        summary = {
            "kind": "summary",
            "n_jobs": args.jobs,
            "hang_job": args.hang_job,
            "hang_tenant": hang_tenant,
            "identical": base_dig == tele_dig,
            "n_outputs": len(base_dig),
            "violations": diag["violations"],
            "burn_by_tenant": diag["burn_by_tenant"],
            "violations_exact_for_hung_tenant":
                diag["burn_by_tenant"] == {hang_tenant: 1},
            "profile_captures": diag["profile_captures"],
            "profile_capture_exists": bool(
                diag["profile_path"]
                and os.path.exists(os.path.join(diag["profile_path"],
                                                "span_dump.json"))),
            "telemetry_write_failed": diag["telemetry_write_failed"],
            "final_lint_errors": len(final_lint),
            "tenant_latency": {
                t: {"e2e_p50": q(t, "e2e", "0.5"),
                    "e2e_p99": q(t, "e2e", "0.99"),
                    "queue_wait_p50": q(t, "queue_wait", "0.5"),
                    "queue_wait_p99": q(t, "queue_wait", "0.99")}
                for t in ("tenant_a", "tenant_b")},
            "platform": os.environ.get("JAX_PLATFORMS", ""),
        }
        emit(summary)
        if args.prom_out:
            from sam2consensus_tpu.observability.telemetry import \
                atomic_write_text

            atomic_write_text(args.prom_out, text)
        ok = (summary["identical"]
              and summary["violations_exact_for_hung_tenant"]
              and summary["profile_capture_exists"]
              and summary["final_lint_errors"] == 0)
        return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
