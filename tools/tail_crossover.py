"""Measure the host-counts tail crossover: local XLA CPU vs the chip.

The host-counts pileup (``HostPileupAccumulator``) finishes with the
count tensor in HOST memory, so the fused tail can run in two places:

* the local XLA CPU backend — zero bytes on the link, one-core compute;
* the accelerator — free compute, but the link bills L*6 upload bytes,
  a ~65 ms dispatch round trip, and the packed-output fetch.

This sweeps genome length L and threshold count T, timing the SAME
jitted tail (``ops.fused.vote_packed_simple``) with every operand
committed to each device, and prints one JSON line per (L, T, device).
``_tail_cpu_wins`` in backends/jax_backend.py carries this sweep's
constants (S2C_TAIL_RT_MS / S2C_TAIL_LINK_MBPS / S2C_TAIL_CPU_MPOS_S
override them for a different link or host; S2C_TAIL_DEVICE=cpu|default
forces the placement outright).

Usage:  python tools/tail_crossover.py  (runs on the default platform;
        the cpu rows use jax.devices("cpu") either way)
"""

from __future__ import annotations

import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def sweep():
    import jax
    import numpy as np

    from sam2consensus_tpu.ops import fused
    from sam2consensus_tpu.ops.cutoff import encode_thresholds

    rng = np.random.default_rng(0)
    default = jax.devices()[0]
    try:
        cpu = jax.devices("cpu")[0]
    except RuntimeError:
        cpu = None
    devices = [("default", default)]
    if cpu is not None and cpu != default:
        devices.append(("cpu", cpu))

    for log_l in (18, 19, 20, 21, 22):
        length = 1 << log_l
        counts = rng.integers(0, 120, size=(length, 6), dtype=np.uint8)
        offsets = np.array([0, length // 2, length], dtype=np.int32)
        for n_thr in (1, 3):
            thr = encode_thresholds([0.25, 0.5, 0.75][:n_thr])
            for tag, dev in devices:
                def once():
                    t0 = time.perf_counter()
                    out = fused.vote_packed_simple(
                        jax.device_put(counts, dev),
                        jax.device_put(thr, dev),
                        jax.device_put(offsets, dev),
                        1, None)
                    np.asarray(out)
                    return time.perf_counter() - t0

                once()                        # compile + warm
                best = min(once() for _ in range(3))
                print(json.dumps({
                    "L": length, "T": n_thr, "cells": length * n_thr,
                    "device": tag, "sec": round(best, 4),
                    "upload_mb": round(length * 6 / 1e6, 2),
                }), flush=True)


if __name__ == "__main__":
    sweep()
