#!/usr/bin/env python3
"""Differential ingest fuzzer: hostile-input hardening evidence.

Seeded byte/field-level mutators over the committed fixture corpus
(tests/data/formats_*.sam), asserting for every mutant:

* **strict mode** (``--on-bad-record fail``, the default) raises a
  clean TYPED error — no hang, no interpreter crash, no silent wrong
  output — with identical exception type, message and file offset
  (``exc.s2c_offset``) across the three native text rungs (serial /
  byte-shard / streaming-gzip), and identical type+message on the
  pure-python decoder rung (which has no offset tracking).  A mutant
  that stays VALID SAM must decode to identical counts on every rung.
* **tolerant mode** (``--on-bad-record skip``-equivalent: a
  QuarantineSink attached at the decode layer) completes on every rung
  with byte-identical count tensors, identical insertion tables, and
  identical quarantine verdicts: same bad-record count, same per-reason
  taxonomy, and — among the raw-line native rungs — the same raw
  record set in the same deterministic merge order.
* **BAM rung**: every mutant that still converts to BAM (conversion
  parses, so most byte-garbage can't) runs through BOTH binary decoders
  — the C++ ``s2c_decode_bam`` lane and the pure-python
  ``BamSegmentEncoder`` twin — with the same strict/tolerant parity
  contract between them; a dedicated flavor also flips raw bytes inside
  the uncompressed BAM payload (record-bounded structural damage).

The campaign artifact is JSONL: one row per flavor aggregate plus a
summary row with the headline counters (``crashes`` / ``hangs`` /
``divergences`` must all be 0).  Divergence rows carry the mutant's
seed + flavor so any failure replays exactly.

A third leg (``--network``) fuzzes the streaming-session FRONT DOOR
(serve/stream_server.py) over raw sockets against a live in-process
IngestServer: truncated chunked bodies, lying/oversize Content-Length,
mid-wave connection drops, garbage chunk framing, wrong methods/paths,
sha-mismatch declarations and interleaved-session writes.  The
contract asserted per mutant: every answered request carries a TYPED
4xx/5xx with a machine-readable reason (or the connection dies
client-side on drop flavors — never a hang); and after the whole
barrage the server still answers, a canary session's consensus digest
is UNCHANGED (garbage never mutates absorbed state), and a fresh good
wave still absorbs.  Same crashes/hangs/divergences=0 headline.

Usage:
  python tools/fuzz_ingest.py [--smoke] [--trials N] [--seed S]
                              [--out results.jsonl] [--per-mutant-timeout S]
  python tools/fuzz_ingest.py --network [--smoke] [--out results.jsonl]
  python tools/fuzz_ingest.py --overhead [--repeats N] [--out perf.json]

``--smoke`` is the tier-1 slice (seeded, ~200 mutants, <60 s —
tests/test_fuzz_smoke.py; with ``--network`` a trimmed mutant matrix,
same invariants).  ``--overhead`` instead measures tolerant-mode
decode overhead on CLEAN input (the sink attached but never hit: the C
fast path must stay ~free) and writes a small JSON artifact for
PERF.md.
"""

import argparse
import gzip
import hashlib
import io
import json
import os
import signal
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sam2consensus_tpu.utils.platform import pin_platform_from_env  # noqa: E402

pin_platform_from_env()

import numpy as np                                               # noqa: E402

from sam2consensus_tpu import native                             # noqa: E402
from sam2consensus_tpu.encoder.events import (GenomeLayout,      # noqa: E402
                                              ReadEncoder,
                                              group_insertions)
from sam2consensus_tpu.encoder.native_encoder import \
    NativeReadEncoder                                            # noqa: E402
from sam2consensus_tpu.encoder.parallel_decode import \
    ParallelFusedDecoder                                         # noqa: E402
from sam2consensus_tpu.ingest.badrecords import (BadRecordPolicy,  # noqa: E402
                                                 QuarantineSink)
from sam2consensus_tpu.io.sam import (ReadStream, opener,        # noqa: E402
                                      read_header)

DATA = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "data")

#: exception types the strict decode contract is allowed to raise — the
#: oracle-parity set (ValueError covers EncodeError + BamParseError;
#: KeyError/IndexError are the reference's own failure modes) plus
#: UnicodeDecodeError for non-ascii bytes.  Anything else that escapes
#: a strict decode is a CRASH finding.
TYPED_ERRORS = (ValueError, KeyError, IndexError, UnicodeDecodeError)


class MutantHang(BaseException):
    """Raised by the per-mutant SIGALRM watchdog: a decode rung wedged."""


# ---------------------------------------------------------------------------
# corpus
# ---------------------------------------------------------------------------
def load_corpus(smoke: bool):
    """(name, text) seeds.  Families are trimmed so a single mutant's
    whole rung matrix stays in the millisecond range — coverage comes
    from mutant count, not input size."""
    out = []
    for stem, max_body in (("formats_adversarial", None),
                           ("formats_short", 160),
                           ("formats_longread", None if smoke else 40)):
        if max_body is None and stem == "formats_longread" and smoke:
            continue
        path = os.path.join(DATA, f"{stem}.sam")
        if not os.path.exists(path):
            continue
        lines = open(path).read().splitlines(keepends=True)
        head = [ln for ln in lines if ln.startswith("@")]
        body = [ln for ln in lines if not ln.startswith("@")]
        if max_body is not None:
            body = body[:max_body]
        out.append((stem, "".join(head + body)))
    if not out:
        raise SystemExit("fuzz_ingest: no fixture corpus under tests/data")
    return out


def corpus_refs(text: str):
    """(refname, reflen) pairs from the header."""
    refs = []
    for ln in text.splitlines():
        if ln.startswith("@SQ"):
            name = length = None
            for f in ln.split("\t"):
                if f.startswith("SN:"):
                    name = f[3:].strip()
                elif f.startswith("LN:"):
                    length = int(f[3:])
            if name:
                refs.append((name, length or 0))
    return refs


# ---------------------------------------------------------------------------
# mutators (text level)
# ---------------------------------------------------------------------------
def _body_indices(lines):
    return [i for i, ln in enumerate(lines) if not ln.startswith("@")]


def _mutate_field(rng, line: str, refs) -> str:
    """Field-level malformation drawn from the taxonomy."""
    f = line.rstrip("\n").split("\t")
    if len(f) < 10:
        return "mangled\tline\n"
    kind = rng.choice(["short_line", "bad_pos", "unknown_ref",
                       "empty_rname", "bad_cigar", "seq_cigar",
                       "bad_alphabet", "oob_pos", "huge_pos",
                       "drop_tail"])
    if kind == "short_line":
        f = f[:rng.choice([1, 3, 5])]
    elif kind == "bad_pos":
        f[3] = rng.choice(["xx", "", "1.5", "0x10"])
    elif kind == "unknown_ref":
        f[2] = "NOSUCHREF" + str(rng.randrange(10))
    elif kind == "empty_rname":
        f[2] = rng.choice(["", " "])
    elif kind == "bad_cigar":
        # garbage text ops are regex-dropped like the reference, so a
        # mutated CIGAR may legitimately stay valid (e.g. ops vanish)
        f[5] = rng.choice(["QQ", "1Z4M", "4M9", "M", "999999999M"])
    elif kind == "seq_cigar":
        f[9] = f[9][: max(1, len(f[9]) // 2)]
    elif kind == "bad_alphabet":
        s = list(f[9])
        s[rng.randrange(len(s))] = rng.choice("acgt!xRY@")
        f[9] = "".join(s)
    elif kind == "oob_pos":
        reflen = dict(refs).get(f[2], 1000)
        f[3] = str((reflen or 1000) * 10)
    elif kind == "huge_pos":
        f[3] = "9" * 15
    elif kind == "drop_tail":
        f = f[:9]
    return "\t".join(f) + "\n"


def mutate_text(rng, text: str, refs):
    """One mutant: (flavor, mutated_text)."""
    lines = text.splitlines(keepends=True)
    body = _body_indices(lines)
    flavor = rng.choice(["field", "field", "field", "splice",
                         "byte_flip", "byte_insert", "byte_delete",
                         "truncate", "non_ascii", "empty_line",
                         "dup_line"])
    if not body:
        flavor = "splice"
    if flavor == "field":
        k = rng.choice(body)
        lines[k] = _mutate_field(rng, lines[k], refs)
    elif flavor == "splice":
        refname = refs[0][0] if refs else "c1"
        junk = rng.choice([
            "broken\tline\n", "\t\t\t\n", "@late header\n",
            f"r\t0\t{refname}\t1\t60\t4M\t*\t0\t0\tAC!T\t*\n",
            "r\t0\t\t\t\t\t\t\t\t\t\n",
        ])
        lines.insert(rng.choice(body) if body else len(lines), junk)
    elif flavor in ("byte_flip", "byte_insert", "byte_delete"):
        k = rng.choice(body)
        raw = bytearray(lines[k].encode("latin-1"))
        p = rng.randrange(max(1, len(raw) - 1))
        if flavor == "byte_flip":
            raw[p] ^= 1 << rng.randrange(7)   # keep it ascii-plane
        elif flavor == "byte_insert":
            raw.insert(p, rng.choice(b"\t\x00 ~Z"))
        else:
            del raw[p]
        lines[k] = raw.decode("latin-1")
    elif flavor == "truncate":
        k = rng.choice(body)
        cut = rng.randrange(1, max(2, len(lines[k])))
        lines = lines[:k] + [lines[k][:cut]]
    elif flavor == "non_ascii":
        k = rng.choice(body)
        raw = bytearray(lines[k].encode("latin-1"))
        raw[rng.randrange(max(1, len(raw) - 1))] = 0xFF
        lines[k] = raw.decode("latin-1")
    elif flavor == "empty_line":
        lines.insert(rng.choice(body), "\n")
    elif flavor == "dup_line":
        k = rng.choice(body)
        lines.insert(k, lines[k])
    return flavor, "".join(lines)


# ---------------------------------------------------------------------------
# rung drivers (decode layer: counts + insertions + quarantine verdicts)
# ---------------------------------------------------------------------------
def _sink():
    return QuarantineSink(BadRecordPolicy(mode="quarantine",
                                          sidecar_max=10_000))


def _digest(layout, counts, enc_like, n_lines):
    grouped = group_insertions(enc_like.insertions, layout)
    h = hashlib.sha256(np.ascontiguousarray(counts).tobytes())
    if grouped is not None:
        # the insertion vote scatter-adds (ev_key, ev_col, ev_code)
        # rows, so EVENT ORDER is decode-order noise (rung replay lanes
        # legitimately reorder wide/flagged reads): canonicalize to the
        # sorted row multiset before hashing
        ev = np.stack([grouped["ev_key"], grouped["ev_col"],
                       grouped["ev_code"]], axis=1)
        ev = ev[np.lexsort(ev.T[::-1])]
        h.update(np.ascontiguousarray(ev).tobytes())
        for k in ("key_contig", "key_local", "key_flat", "n_cols"):
            h.update(np.ascontiguousarray(grouped[k]).tobytes())
        h.update(str(grouped["max_cols"]).encode())
    return (h.hexdigest()[:16], int(enc_like.n_reads),
            int(enc_like.n_skipped), int(n_lines))


def _verdict(sink):
    return (sink.count, tuple(sorted(sink.reason_counts().items())))


def _err_key(exc, with_offset=True, with_msg=True):
    return (type(exc).__name__,
            str(exc) if with_msg else None,
            getattr(exc, "s2c_offset", None) if with_offset else None)


def run_text_rung(rung: str, data: bytes, tolerant: bool, tmp: str):
    """One decode-layer pass; returns ("ok", digest, verdict, raws) or
    ("err", err_key).  ``raws`` is the merged raw-record list for the
    native raw-line rungs (None on the py rung: it stores rendered
    records, compared by reason only)."""
    sink = _sink() if tolerant else None
    if rung == "py":
        return _run_py_rung(data, sink, tmp)
    if rung in ("serial", "shard"):
        path = os.path.join(tmp, "m.sam")
        with open(path, "wb") as fh:
            fh.write(data)
        handle = opener(path, binary=True)
    else:                                      # stream rung: gzip file
        path = os.path.join(tmp, "m.sam.gz")
        with gzip.open(path, "wb") as fh:
            fh.write(data)
        handle = opener(path, binary=True)
    try:
        contigs, _n, first = read_header(handle)
        layout = GenomeLayout(contigs)
        stream = ReadStream(handle, first)
        counts = np.zeros((layout.total_len, 6), dtype=np.int32)
        if rung == "serial":
            enc = NativeReadEncoder(layout, accumulate_into=counts,
                                    bad_sink=sink,
                                    on_lines=stream.add_lines,
                                    on_bytes=stream.add_bytes)
            for _ in enc.encode_blocks_from(stream):
                pass
            like = enc
        else:
            dec = ParallelFusedDecoder(layout, counts,
                                       n_threads=3 if rung == "shard"
                                       else 2, bad_sink=sink,
                                       on_lines=stream.add_lines,
                                       on_bytes=stream.add_bytes)
            for _ in dec.encode_input(stream, min_shard_bytes=1):
                pass
            like = dec
        n_lines = stream.n_lines
    finally:
        handle.close()
    return ("ok", _digest(layout, counts, like, n_lines),
            None if sink is None else _verdict(sink),
            None if sink is None
            else [e["record"] for e in sink.entries()])


def _run_py_rung(data: bytes, sink, tmp: str):
    """Pure-python rung: batch scatter into a count tensor (the portable
    twin of the fused native accumulation).  Reads through the REAL
    text-mode handle (``opener``: ascii, errors=strict) — a non-ascii
    body byte surfaces as the line iterator's UnicodeDecodeError on
    this rung, job-level in every mode (the text-handle contract;
    documented in README Failure semantics)."""
    path = os.path.join(tmp, "m_py.sam")
    with open(path, "wb") as fh:
        fh.write(data)
    handle = opener(path)
    try:
        contigs, _n, first = read_header(handle)
        layout = GenomeLayout(contigs)
        stream = ReadStream(handle, first)
        enc = ReadEncoder(layout, bad_sink=sink)
        on_bad = None
        if sink is not None:
            def on_bad(line, exc):
                # parse-level quarantine counts a skip, like the
                # production lanes (jax py rung / cpu backend)
                sink.record(line, exc)
                enc.n_skipped += 1
        counts = np.zeros((layout.total_len, 6), dtype=np.int32)
        for b in enc.encode_segments(stream.records(on_bad=on_bad), 4096):
            for _w, (starts, codes) in b.buckets.items():
                rows, cols = np.nonzero(codes != 255)
                np.add.at(counts, (starts[rows].astype(np.int64) + cols,
                                   codes[rows, cols]), 1)
        n_lines = stream.n_lines
    finally:
        handle.close()
    return ("ok", _digest(layout, counts, enc, n_lines),
            None if sink is None else _verdict(sink), None)


def run_bam_rung(decoder: str, path: str, tolerant: bool):
    """BAM decode-layer pass via make_encoder; same return shape as
    run_text_rung (raws=None: BAM stores rendered records)."""
    from sam2consensus_tpu.config import RunConfig
    from sam2consensus_tpu.formats import open_alignment_input

    sink = _sink() if tolerant else None
    ai = open_alignment_input(path, fallback=False)
    try:
        layout = GenomeLayout(ai.contigs)
        counts = np.zeros((layout.total_len, 6), dtype=np.int32)
        enc, batches = ai.stream.make_encoder(
            layout, RunConfig(prefix="f", decoder=decoder),
            bad_sink=sink)
        for b in batches:
            for _w, (starts, codes) in b.buckets.items():
                rows, cols = np.nonzero(codes != 255)
                np.add.at(counts, (starts[rows].astype(np.int64) + cols,
                                   codes[rows, cols]), 1)
        dig = _digest(layout, counts, enc, ai.stream.n_lines)
    finally:
        ai.close()
    return ("ok", dig, None if sink is None else _verdict(sink), None)


# ---------------------------------------------------------------------------
# the differential check for one mutant
# ---------------------------------------------------------------------------
TEXT_RUNGS = ("serial", "shard", "stream", "py")


def check_text_mutant(data: bytes, tmp: str):
    """Run the strict + tolerant rung matrices; return a list of
    divergence strings (empty = clean)."""
    div = []
    # the py differential lane reads through the REAL text-mode handle
    # (ascii-strict, universal newlines — the reference oracle's own
    # contract), which differs from the `\n`-delimited byte-oriented
    # native rungs on exactly two byte classes: non-ascii (job-level
    # UnicodeDecodeError) and a bare CR (universal newlines splits the
    # line where the native rungs, per the SAM spec, do not).  Both are
    # DOCUMENTED lane differences (README Failure semantics), scoped out
    # of the py comparison only — the four production rungs must still
    # agree with each other on every mutant.
    bare_cr = b"\r" in data.replace(b"\r\n", b"")
    # -- strict: outcome parity ------------------------------------------
    outcomes = {}
    for rung in TEXT_RUNGS:
        try:
            outcomes[rung] = run_text_rung(rung, data, False, tmp)
        except TYPED_ERRORS as exc:
            outcomes[rung] = ("err", _err_key(exc))
        except MutantHang:
            raise
        except BaseException as exc:      # noqa: BLE001 - crash finding
            div.append(f"strict CRASH on {rung}: "
                       f"{type(exc).__name__}: {exc}")
            outcomes[rung] = ("crash",)
    ref = outcomes["serial"]
    for rung in ("shard", "stream"):
        if outcomes[rung] != ref and "crash" not in (
                outcomes[rung][0], ref[0]):
            div.append(f"strict divergence serial vs {rung}: "
                       f"{ref} != {outcomes[rung]}")
    # py rung: type+message parity, no offset tracking.  Unicode errors
    # compare by type only: the ascii text handle reports the byte's
    # position in its own read chunk, the native replay in the line.
    po, so = outcomes["py"], ref
    if "crash" not in (po[0], so[0]) and not bare_cr:
        if po[0] == "err" and po[1][0] == "UnicodeDecodeError" \
                and so[0] == "ok":
            # lane difference: the py rung's ascii text handle dies on
            # ANY non-ascii byte, while the byte-fed native rungs only
            # validate semantically-relevant fields (a 0xFF in
            # QNAME/QUAL decodes fine)
            pass
        elif po[0] != so[0]:
            div.append(f"strict divergence serial vs py: {so} != {po}")
        elif po[0] == "err" and po[1][:2] != so[1][:2] \
                and not (po[1][0] == so[1][0]
                         == "UnicodeDecodeError"):
            div.append(f"strict error divergence serial vs py: "
                       f"{so[1]} != {po[1]}")
        elif po[0] == "ok" and po[1][0] != so[1][0]:
            div.append(f"strict counts divergence serial vs py: "
                       f"{so[1]} != {po[1]}")
    # -- tolerant: completion + identical verdicts -----------------------
    tol = {}
    for rung in TEXT_RUNGS:
        try:
            tol[rung] = run_text_rung(rung, data, True, tmp)
        except TYPED_ERRORS as exc:
            # job-level failures stay legal in tolerant mode (header
            # damage, container loss) — but must agree across rungs
            tol[rung] = ("err", _err_key(exc, with_offset=False))
        except MutantHang:
            raise
        except BaseException as exc:      # noqa: BLE001
            div.append(f"tolerant CRASH on {rung}: "
                       f"{type(exc).__name__}: {exc}")
            tol[rung] = ("crash",)
    ref = tol["serial"]
    for rung in ("shard", "stream"):
        t = tol[rung]
        if "crash" in (t[0], ref[0]):
            continue
        if t[:3] != ref[:3]:
            div.append(f"tolerant divergence serial vs {rung}: "
                       f"{ref[:3]} != {t[:3]}")
        elif t[0] == "ok" and t[3] != ref[3]:
            div.append(f"tolerant raw-record divergence serial vs "
                       f"{rung}: {ref[3]} != {t[3]}")
    # py rung tolerant: the ascii text handle makes a non-ascii byte a
    # job-level UnicodeDecodeError on this lane (the iterator cannot
    # resume past it), where the byte-fed native rungs quarantine the
    # one record — a DOCUMENTED lane difference, not a divergence
    t = tol["py"]
    nonascii = (t[0] == "err" and t[1][0] == "UnicodeDecodeError") or \
        (ref[0] == "ok" and ref[2] is not None
         and any(r == "non_ascii" for r, _n in ref[2][1]))
    if "crash" in (t[0], ref[0]) or nonascii or bare_cr:
        pass
    elif t[0] == ref[0]:
        if t[0] == "ok" and (t[1] != ref[1] or t[2] != ref[2]):
            div.append(f"tolerant divergence serial vs py: "
                       f"{ref[1:3]} != {t[1:3]}")
    else:
        div.append(f"tolerant outcome divergence serial vs py: "
                   f"{ref[0]} != {t[0]}")
    # strict-ok mutants must stay byte-identical under tolerance
    if outcomes["serial"][0] == "ok" and ref[0] == "ok":
        if outcomes["serial"][1][0] != ref[1][0]:
            div.append("tolerant mode changed counts on a VALID input")
        if ref[2][0] != 0:
            div.append("tolerant mode quarantined records on input "
                       "strict mode accepts")
    return div


def check_bam_mutant(text: str, rng, tmp: str, binary_flip: bool):
    """BAM leg: convert (skip mutant if unconvertible), optionally flip
    a payload byte, then native-vs-python parity strict + tolerant."""
    from sam2consensus_tpu.formats.bam import (bam_payload,
                                               sam_text_to_records)
    from sam2consensus_tpu.formats.bgzf import BGZF_EOF, compress_block

    try:
        payload = bam_payload(*sam_text_to_records(text))
    except Exception:                     # noqa: BLE001 - unconvertible
        return None
    if binary_flip and len(payload) > 64:
        raw = bytearray(payload)
        # stay past the header region so the mutation is record-shaped
        lo = min(len(raw) - 1, 48)
        p = rng.randrange(lo, len(raw))
        raw[p] ^= 1 << rng.randrange(8)
        payload = bytes(raw)
    path = os.path.join(tmp, "m.bam")
    with open(path, "wb") as fh:
        frames = [compress_block(payload[o:o + 60000])
                  for o in range(0, len(payload), 60000)]
        fh.write(b"".join(frames) + BGZF_EOF)

    div = []
    decoders = ("native", "py") if native.load() is not None else ("py",)
    for tolerant in (False, True):
        outs = {}
        for dec in decoders:
            try:
                outs[dec] = run_bam_rung(dec, path, tolerant)
            except TYPED_ERRORS as exc:
                outs[dec] = ("err", _err_key(exc, with_offset=False))
            except MutantHang:
                raise
            except BaseException as exc:  # noqa: BLE001
                div.append(f"bam {'tolerant' if tolerant else 'strict'} "
                           f"CRASH on {dec}: {type(exc).__name__}: {exc}")
                outs[dec] = ("crash",)
        if len(outs) == 2 and "crash" not in (outs["native"][0],
                                              outs["py"][0]):
            a, b = outs["native"], outs["py"]
            if a[0] != b[0]:
                div.append(f"bam outcome divergence native vs py "
                           f"(tolerant={tolerant}): {a[0]} != {b[0]}")
            elif a[0] == "ok" and (a[1][0] != b[1][0] or a[2] != b[2]):
                div.append(f"bam divergence native vs py "
                           f"(tolerant={tolerant}): {a[1:3]} != {b[1:3]}")
            elif a[0] == "err" and a[1][0] != b[1][0]:
                div.append(f"bam error-type divergence native vs py: "
                           f"{a[1]} != {b[1]}")
    return div


# ---------------------------------------------------------------------------
# campaign driver
# ---------------------------------------------------------------------------
def run_campaign(args) -> int:
    import random

    rng = random.Random(args.seed)
    corpus = load_corpus(args.smoke)
    rows = []
    t_start = time.time()
    crashes = hangs = divergences = 0
    per_flavor: dict = {}
    bam_legs = 0

    def alarm(_sig, _frm):
        raise MutantHang()

    has_alarm = hasattr(signal, "SIGALRM")
    if has_alarm:
        signal.signal(signal.SIGALRM, alarm)

    for trial in range(args.trials):
        name, text = corpus[trial % len(corpus)]
        refs = corpus_refs(text)
        seed = rng.randrange(1 << 30)
        mrng = __import__("random").Random(seed)
        flavor, mutated = mutate_text(mrng, text, refs)
        per_flavor[flavor] = per_flavor.get(flavor, 0) + 1
        data = mutated.encode("latin-1")
        if has_alarm:
            signal.alarm(args.per_mutant_timeout)
        try:
            with tempfile.TemporaryDirectory() as tmp:
                div = check_text_mutant(data, tmp)
                # every ~4th mutant also runs the BAM leg (conversion
                # cost), alternating clean-convert and binary-flip
                if trial % 4 == 0:
                    bdiv = check_bam_mutant(mutated, mrng, tmp,
                                            binary_flip=bool(trial % 8))
                    if bdiv is not None:
                        bam_legs += 1
                        div += bdiv
        except MutantHang:
            hangs += 1
            rows.append({"kind": "hang", "trial": trial, "seed": seed,
                         "corpus": name, "flavor": flavor})
            print(f"HANG trial {trial} [{flavor}] seed={seed}",
                  file=sys.stderr)
            break                      # the process state is suspect now
        finally:
            if has_alarm:
                signal.alarm(0)
        for d in div:
            kind = "crash" if "CRASH" in d else "divergence"
            if kind == "crash":
                crashes += 1
            else:
                divergences += 1
            rows.append({"kind": kind, "trial": trial, "seed": seed,
                         "corpus": name, "flavor": flavor, "detail": d})
            print(f"{kind.upper()} trial {trial} [{name}/{flavor}] "
                  f"seed={seed}: {d}", file=sys.stderr)
        if args.progress and trial % 50 == 49:
            print(f"... {trial + 1}/{args.trials} "
                  f"({time.time() - t_start:.1f}s)",
                  file=sys.stderr, flush=True)

    summary = {
        "kind": "summary", "schema": "s2c-fuzz-ingest/1",
        "mode": "smoke" if args.smoke else "full",
        "trials": args.trials, "seed": args.seed,
        "corpus": [n for n, _t in corpus],
        "flavors": dict(sorted(per_flavor.items())),
        "bam_legs": bam_legs,
        "crashes": crashes, "hangs": hangs, "divergences": divergences,
        "elapsed_sec": round(time.time() - t_start, 2),
        "native": native.load() is not None,
    }
    rows.append(summary)
    if args.out == "-":
        # campaign mode (tools/tpu_campaign.sh run_step captures
        # stdout as the artifact): rows to stdout, summary to stderr
        for r in rows:
            print(json.dumps(r))
    elif args.out:
        with open(args.out, "w") as fh:
            for r in rows:
                fh.write(json.dumps(r) + "\n")
    print(f"FUZZ INGEST: trials={args.trials} bam_legs={bam_legs} "
          f"crashes={crashes} hangs={hangs} divergences={divergences} "
          f"elapsed={summary['elapsed_sec']}s "
          + ("CLEAN" if not (crashes or hangs or divergences)
             else "FINDINGS"),
          file=sys.stderr if args.out == "-" else sys.stdout)
    return 1 if (crashes or hangs or divergences) else 0


# ---------------------------------------------------------------------------
# tolerant-mode overhead on clean input (PERF.md evidence)
# ---------------------------------------------------------------------------
def run_overhead(args) -> int:
    path = os.path.join(DATA, "formats_short.sam")
    text = open(path).read()
    # amortize fixed per-run costs (sink construction, per-block python
    # bookkeeping) over a realistic decode: the committed fixture body
    # replicated ~50x (~4 MB) — the <2% claim is about the per-record
    # fast path, which the C decoder runs UNCHANGED in tolerant mode
    head = "".join(ln for ln in text.splitlines(keepends=True)
                   if ln.startswith("@"))
    body = "".join(ln for ln in text.splitlines(keepends=True)
                   if not ln.startswith("@"))
    data = (head + body * 50).encode("ascii")
    results = {}
    with tempfile.TemporaryDirectory() as tmp:
        for rung in ("serial", "shard"):
            strict_s, tol_s = [], []
            for rep in range(args.repeats):
                # alternate lane order per repeat and take min-of-N:
                # scheduler noise on a shared host is one-sided, so the
                # minimum is the honest estimate of the code's own cost
                lanes = ((False, strict_s), (True, tol_s))
                if rep % 2:
                    lanes = tuple(reversed(lanes))
                for tolerant, lane in lanes:
                    t0 = time.perf_counter()
                    out = run_text_rung(rung, data, tolerant, tmp)
                    lane.append(time.perf_counter() - t0)
                    assert out[0] == "ok"
                    if tolerant:
                        assert out[2][0] == 0, "clean corpus hit the sink"
            s, t = min(strict_s), min(tol_s)
            results[rung] = {"strict_sec": round(s, 6),
                             "tolerant_sec": round(t, 6),
                             "overhead_pct": round(100.0 * (t - s) / s, 2)}
    artifact = {"schema": "s2c-tolerant-overhead/1",
                "input": os.path.basename(path),
                "input_bytes": len(data),
                "repeats": args.repeats, "rungs": results,
                "native": native.load() is not None}
    out = args.out or "perf/tolerant_overhead.json"
    if out == "-":
        json.dump(artifact, sys.stdout, indent=1)
        sys.stdout.write("\n")
    else:
        with open(out, "w") as fh:
            json.dump(artifact, fh, indent=1)
            fh.write("\n")
    print("overhead "
          + " ".join(f"{r}={v['overhead_pct']}%"
                     for r, v in results.items()),
          file=sys.stderr if out == "-" else sys.stdout)
    return 0


# ---------------------------------------------------------------------------
# network-framing leg: the streaming-session front door under fire
# ---------------------------------------------------------------------------
_NET_HEADER = "@HD\tVN:1.6\n@SQ\tSN:fuzzref\tLN:24\n"
_NET_READ = ("fr1\t0\tfuzzref\t1\t60\t24M\t*\t0\t0\t"
             "ACGTACGTACGTACGTACGTACGT\t"
             "IIIIIIIIIIIIIIIIIIIIIIII\n")


def _http_exchange(port, payload: bytes, read_reply=True,
                   half_close=False, timeout=10.0):
    """One raw-socket exchange; returns the reply status int, or None
    when the client tears the connection / the server cannot answer."""
    import socket as _socket

    s = _socket.create_connection(("127.0.0.1", port), timeout=timeout)
    try:
        s.sendall(payload)
        if half_close:
            s.shutdown(_socket.SHUT_WR)
        if not read_reply:
            return None
        s.settimeout(timeout)
        buf = b""
        while b"\r\n" not in buf:
            chunk = s.recv(4096)
            if not chunk:
                return None
            buf += chunk
        return int(buf.split(b"\r\n", 1)[0].split()[1])
    except (_socket.timeout, ConnectionError, OSError):
        return None
    finally:
        s.close()


def _req(method, path, body=b"", headers=(), chunks=None,
         no_length=False):
    """Assemble a raw HTTP/1.1 request.  ``chunks`` switches to chunked
    framing: a list of (size_line, data, trailer_crlf) triples sent
    verbatim — malformed framing is the point."""
    head = [f"{method} {path} HTTP/1.1", "Host: 127.0.0.1",
            "Connection: close"]
    head += [f"{k}: {v}" for k, v in headers]
    if chunks is not None:
        head.append("Transfer-Encoding: chunked")
        body = b"".join(sz + data + tail for sz, data, tail in chunks)
    elif not no_length:
        head.append(f"Content-Length: {len(body)}")
    return ("\r\n".join(head) + "\r\n\r\n").encode("ascii") + body


def _net_flavors(sid: str, max_body: int):
    """(name, payload, half_close, expected_statuses) — expected=None
    means any answer is fine as long as the server survives (client-
    drop flavors where no reply can be delivered)."""
    wave = f"/session/{sid}/wave"
    good = _NET_READ.encode()
    return [
        ("truncated_chunked",
         _req("POST", wave, chunks=[(b"18\r\n", good[:12], b"")]),
         True, {400, 408}),
        ("bad_chunk_hex",
         _req("POST", wave, chunks=[(b"zz\r\n", b"", b"")]),
         False, {400}),
        ("bad_chunk_framing",
         _req("POST", wave,
              chunks=[(b"4\r\n", b"ACGT", b"XX"),
                      (b"0\r\n", b"", b"\r\n")]),
         False, {400}),
        ("oversize_chunk",
         _req("POST", wave,
              chunks=[(hex(max_body + 9)[2:].encode() + b"\r\n",
                       b"", b"")]),
         False, {413}),
        ("oversize_content_length",
         _req("POST", wave, headers=[("Content-Length",
                                      str(max_body + 9))],
              no_length=True), False, {413}),
        ("negative_content_length",
         _req("POST", wave, headers=[("Content-Length", "-5")],
              no_length=True), False, {400}),
        ("bad_content_length",
         _req("POST", wave, headers=[("Content-Length", "4x")],
              no_length=True), False, {400}),
        ("no_length",
         _req("POST", wave, no_length=True), False, {400}),
        ("mid_wave_drop",
         _req("POST", wave, headers=[("Content-Length", "5000")],
              no_length=True) + good, True, {400, 408, None}),
        ("malformed_wave",
         _req("POST", wave, body=b"not\ta\tsam\tline\n"),
         False, {422}),
        ("empty_wave",
         _req("POST", wave, body=b""), False, {422}),
        ("sha_mismatch",
         _req("POST", wave, body=good,
              headers=[("X-Wave-Sha256", "0" * 64)]), False, {422}),
        ("non_utf8_header",
         _req("POST", "/session/open", body=b"@SQ\xff\xfe\n"),
         False, {422}),
        ("unknown_session",
         _req("POST", "/session/nosuchsid/wave", body=good),
         False, {404}),
        ("bad_verb",
         _req("POST", f"/session/{sid}/frobnicate", body=b""),
         False, {404}),
        ("bad_path",
         _req("POST", "/frobnicate", body=b""), False, {404}),
        ("bad_method",
         _req("PUT", wave, body=good), False, {405}),
        ("get_unknown",
         _req("GET", "/session/nosuchsid"), False, {404}),
    ]


def run_network_campaign(args) -> int:
    """The front-door leg: every mutant against a LIVE IngestServer,
    then the survival + digest-invariance postconditions."""
    import shutil
    import urllib.request

    from sam2consensus_tpu.serve import IngestServer, ServeRunner
    from sam2consensus_tpu.serve.session import SessionManager

    rows = []
    t_start = time.time()
    crashes = hangs = divergences = 0
    tmp = tempfile.mkdtemp(prefix="s2c_fuzz_net_")
    runner = ServeRunner(prewarm="off", decode_ahead=False,
                         echo=lambda *a, **k: None,
                         journal_dir=os.path.join(tmp, "journal"))
    manager = SessionManager(runner, _net_base_cfg(tmp))
    max_body = 1 << 20
    server = IngestServer(manager, port=0, max_body=max_body,
                          timeout=3.0)
    port = server.port

    def api(method, path, body=b"", headers=None):
        r = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                   data=body, method=method,
                                   headers=headers or {})
        with urllib.request.urlopen(r, timeout=120) as resp:
            return json.loads(resp.read())

    try:
        # canary session: two good waves absorbed, digest recorded
        sid = api("POST", "/session/open", _NET_HEADER.encode())["sid"]
        for _ in range(2):
            api("POST", f"/session/{sid}/wave", _NET_READ.encode())
        before = api("GET", f"/session/{sid}")
        flavors = _net_flavors(sid, max_body)
        rounds = 2 if args.smoke else 8
        for rnd in range(rounds):
            for name, payload, half_close, expected in flavors:
                t0 = time.time()
                try:
                    status = _http_exchange(port, payload,
                                            half_close=half_close,
                                            timeout=8.0)
                except Exception as exc:   # noqa: BLE001
                    crashes += 1
                    rows.append({"kind": "crash", "flavor": name,
                                 "round": rnd, "detail": repr(exc)})
                    continue
                el = time.time() - t0
                if el > 7.5:
                    hangs += 1
                    rows.append({"kind": "hang", "flavor": name,
                                 "round": rnd,
                                 "elapsed_sec": round(el, 2)})
                elif expected is not None and status not in expected:
                    divergences += 1
                    rows.append({
                        "kind": "divergence", "flavor": name,
                        "round": rnd, "status": status,
                        "detail": f"expected {sorted(map(str, expected))}, "
                                  f"got {status}"})
            # interleaved-session writes: two sessions' waves racing on
            # parallel connections must both absorb cleanly
            sid2 = api("POST", "/session/open",
                       _NET_HEADER.encode())["sid"]
            import threading as _threading
            errs = []

            def _w(target_sid):
                try:
                    r = api("POST", f"/session/{target_sid}/wave",
                            _NET_READ.encode())
                    if r.get("status") not in ("absorbed", "pending"):
                        errs.append(r)
                except Exception as exc:   # noqa: BLE001
                    errs.append(repr(exc))

            ts = [_threading.Thread(target=_w, args=(s,))
                  for s in (sid, sid2, sid, sid2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=120)
            if errs:
                divergences += 1
                rows.append({"kind": "divergence",
                             "flavor": "interleaved_sessions",
                             "round": rnd, "detail": repr(errs[:3])})
            api("POST", f"/session/{sid2}/close", b"")
        # -- postconditions ------------------------------------------
        after = api("GET", f"/session/{sid}")
        if after["digest"] != before["digest"]:
            divergences += 1
            rows.append({"kind": "divergence", "flavor": "postcondition",
                         "detail": "canary digest moved under garbage: "
                                   f"{before['digest']} -> "
                                   f"{after['digest']}"})
        # waves absorbed during the barrage are the interleaved GOOD
        # ones only; rejected garbage must never have counted
        final = api("POST", f"/session/{sid}/wave", _NET_READ.encode())
        if final.get("status") not in ("absorbed", "pending"):
            crashes += 1
            rows.append({"kind": "crash", "flavor": "postcondition",
                         "detail": f"good wave no longer absorbs: "
                                   f"{final}"})
        audit = runner.journal.audit()
        bad = {s: a for s, a in audit.get("sessions", {}).items()
               if a["duplicated_waves"] or a["lost_waves"]}
        if bad:
            divergences += 1
            rows.append({"kind": "divergence", "flavor": "postcondition",
                         "detail": f"journal audit: {bad}"})
    finally:
        server.close()
        runner.close()
        shutil.rmtree(tmp, ignore_errors=True)

    summary = {
        "kind": "summary", "schema": "s2c-fuzz-ingest-net/1",
        "mode": "smoke" if args.smoke else "full",
        "flavors": len(_net_flavors("x", 1)) + 1,
        "rounds": 2 if args.smoke else 8,
        "crashes": crashes, "hangs": hangs, "divergences": divergences,
        "elapsed_sec": round(time.time() - t_start, 2),
    }
    rows.append(summary)
    if args.out == "-":
        for r in rows:
            print(json.dumps(r))
    elif args.out:
        with open(args.out, "w") as fh:
            for r in rows:
                fh.write(json.dumps(r) + "\n")
    print(f"FUZZ INGEST NET: rounds={summary['rounds']} "
          f"crashes={crashes} hangs={hangs} divergences={divergences} "
          f"elapsed={summary['elapsed_sec']}s "
          + ("CLEAN" if not (crashes or hangs or divergences)
             else "FINDINGS"),
          file=sys.stderr if args.out == "-" else sys.stdout)
    return 1 if (crashes or hangs or divergences) else 0


def _net_base_cfg(tmp: str):
    from sam2consensus_tpu.config import RunConfig
    return RunConfig(prefix="fuzz", outfolder=tmp + os.sep)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1 slice: ~200 mutants, <60 s")
    ap.add_argument("--network", action="store_true",
                    help="fuzz the streaming-session ingest endpoint "
                         "over raw sockets instead of the decode layer")
    ap.add_argument("--overhead", action="store_true",
                    help="measure tolerant-mode overhead on clean input")
    ap.add_argument("--trials", type=int, default=None)
    ap.add_argument("--seed", type=int, default=90210)
    ap.add_argument("--repeats", type=int, default=9)
    ap.add_argument("--out", default=None)
    ap.add_argument("--per-mutant-timeout", type=int, default=None,
                    help="SIGALRM hang watchdog per mutant (seconds)")
    ap.add_argument("--no-progress", dest="progress",
                    action="store_false")
    args = ap.parse_args()
    if args.overhead:
        return run_overhead(args)
    if args.network:
        return run_network_campaign(args)
    if args.trials is None:
        args.trials = 200 if args.smoke else 1200
    if args.per_mutant_timeout is None:
        args.per_mutant_timeout = 30 if args.smoke else 120
    return run_campaign(args)


if __name__ == "__main__":
    sys.exit(main())
