#!/usr/bin/env python3
"""Noise-aware perf regression gate over the committed bench trajectory.

Usage:
  python tools/regress_check.py                      # BENCH_r*.json in repo root
  python tools/regress_check.py A.json B.json C.json # explicit trajectory
  python tools/regress_check.py --new fresh.json     # gate a candidate
  python tools/regress_check.py --jsonl campaign/x.jsonl \
         --group-by config --value median_sec        # campaign series mode

The trajectory files are driver-wrapper BENCH artifacts (possibly with
head-truncated ``tail`` captures — per-config rows are recovered with a
balanced-object scan) or bare bench JSON lines.  The LAST file (or
``--new``) is the candidate; every earlier file is history.  Per
(config, metric) series the candidate is checked against the history's
median/MAD band (observability/regress.py): fewer than ``--min-repeats``
prior points is ``insufficient_history`` (passes, loudly), a candidate
outside the band in the bad direction is a regression (exit 1), the
good direction an improvement (reported, exit 0).

This is the CI gate (tests/test_regression_gate.py runs it against the
committed BENCH_r01..r05 history) and the engine behind
``tools/bench_report.py --diff``.
"""

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from sam2consensus_tpu.observability import regress  # noqa: E402


def discover_default(root):
    # .full.json siblings are the same round's complete row set, not a
    # separate trajectory point (load_bench_artifact reads them through
    # their BENCH_rNN.json parent)
    return sorted(p for p in glob.glob(os.path.join(root,
                                                    "BENCH_r*.json"))
                  if not p.endswith(".full.json"))


def gate_bench(paths, candidate_path, metrics, k, rel_floor, min_repeats):
    """Verdict rows for every (config, metric) series; the candidate is
    ``candidate_path``'s value, history is every other file's."""
    series = regress.bench_series(paths, metrics=metrics)
    verdicts = []
    for (config, metric), points in sorted(series.items()):
        cand = [v for p, v in points if p == candidate_path]
        hist = [v for p, v in points if p != candidate_path]
        if not cand:
            continue            # config absent from the candidate round
        res = regress.check_series(
            hist, cand[-1],
            lower_is_better=regress.LOWER_IS_BETTER.get(metric, False),
            k=k, rel_floor=rel_floor, min_repeats=min_repeats)
        res.update(config=config, metric=metric)
        verdicts.append(res)
    return verdicts


def gate_jsonl(path, group_by, value_field, k, rel_floor, min_repeats,
               lower_is_better):
    """Per-group verdicts over a campaign JSONL: within each group the
    LAST row is the candidate, earlier rows are history."""
    series = regress.series_from_jsonl(path, group_by, value_field)
    verdicts = []
    for group, values in sorted(series.items()):
        if len(values) < 2:
            continue
        res = regress.check_series(
            values[:-1], values[-1], lower_is_better=lower_is_better,
            k=k, rel_floor=rel_floor, min_repeats=min_repeats)
        res.update(config=group, metric=value_field)
        verdicts.append(res)
    return verdicts


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("files", nargs="*",
                   help="bench artifacts in trajectory order "
                        "(default: BENCH_r*.json in the repo root)")
    p.add_argument("--new", dest="new", default=None,
                   help="candidate artifact (default: last trajectory "
                        "file)")
    p.add_argument("--metric", action="append", default=None,
                   help="per-config metric(s) to gate "
                        "(default: vs_baseline, jax_sec, peak_rss_mb)")
    p.add_argument("--k", type=float, default=regress.DEFAULT_K,
                   help="MAD band width (sigmas; default %(default)s)")
    p.add_argument("--rel-floor", type=float,
                   default=regress.DEFAULT_REL_FLOOR,
                   help="relative noise floor (fraction of the median "
                        "always tolerated; default %(default)s)")
    p.add_argument("--min-repeats", type=int,
                   default=regress.DEFAULT_MIN_REPEATS,
                   help="history points required before the band is "
                        "trusted (default %(default)s)")
    p.add_argument("--jsonl", default=None,
                   help="campaign JSONL series mode (instead of BENCH "
                        "trajectory)")
    p.add_argument("--group-by", default="config",
                   help="JSONL mode: series key field")
    p.add_argument("--value", default="median_sec",
                   help="JSONL mode: numeric field to gate")
    p.add_argument("--lower-is-better", action="store_true",
                   help="JSONL mode: the value regresses upward "
                        "(seconds-like)")
    p.add_argument("--json", action="store_true",
                   help="emit verdicts as JSON instead of a table")
    args = p.parse_args(argv)

    if args.jsonl:
        verdicts = gate_jsonl(args.jsonl, args.group_by, args.value,
                              args.k, args.rel_floor, args.min_repeats,
                              args.lower_is_better)
    else:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        paths = args.files or discover_default(root)
        if args.new:
            paths = [f for f in paths if f != args.new] + [args.new]
        if not paths:
            print("no bench artifacts found", file=sys.stderr)
            return 2
        candidate = args.new or paths[-1]
        # peak_rss_mb rides alongside the time metrics (rows before the
        # memory plane simply contribute no history for it, which the
        # min-repeat rule reports loudly rather than banding on noise)
        metrics = tuple(args.metric or ("vs_baseline", "jax_sec",
                                        "peak_rss_mb"))
        verdicts = gate_bench(paths, candidate, metrics, args.k,
                              args.rel_floor, args.min_repeats)

    regressed = [v for v in verdicts if v["status"] == "regressed"]
    if args.json:
        print(json.dumps({"verdicts": verdicts,
                          "regressed": len(regressed)}, indent=1))
    else:
        print(f"{'series':<40} {'status':<22} {'candidate':>12} "
              f"{'median':>12} {'allowed':>10}")
        for v in verdicts:
            med = "—" if v["median"] is None else f"{v['median']:.4g}"
            allowed = "—" if v["allowed"] is None \
                else f"±{v['allowed']:.3g}"
            label = f"{v['config']}/{v['metric']}"
            status = v["status"]
            if status == "insufficient_history":
                status = f"pass ({v['n_history']} repeats)"
            print(f"{label:<40} {status:<22} {v['candidate']:>12.4g} "
                  f"{med:>12} {allowed:>10}")
        print(f"\n{len(verdicts)} series checked, "
              f"{len(regressed)} regression(s)")
        for v in regressed:
            print(f"REGRESSED: {v['config']}/{v['metric']} = "
                  f"{v['candidate']:.4g} vs median {v['median']:.4g} "
                  f"(allowed ±{v['allowed']:.3g}, "
                  f"n={v['n_history']})")
    return 1 if regressed else 0


if __name__ == "__main__":
    sys.exit(main())
