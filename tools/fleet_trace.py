#!/usr/bin/env python3
"""Fleet flight-recorder assembler: one journal -> one Perfetto trace.

The offline half of observability/flight.py (ISSUE 16): replay a serve
journal directory — any ``--journal DIR`` a fleet ran over, including
the scratch journals tools/fleet_soak.py and tools/chaos_soak.py leave
behind with ``--workdir`` — into

* a **Chrome/Perfetto trace** (``--out trace.json``): per-job tracks
  (queue wait, claim latency, run attempts per worker, steal gaps
  death -> reap -> re-claim), lease renewals/reaps as instants,
  per-worker occupancy lanes, flow arrows job-track -> worker-lane.
  Per-worker ``--trace-out`` artifacts (``--worker-traces GLOB``) are
  merged in, re-anchored from each process's perf_counter epoch onto
  the journal's wall clock and joined by the ``trace_id`` their
  ``s2c`` metadata block carries.  Load at https://ui.perfetto.dev;
* **scheduler telemetry** (always printed as a JSON summary): per-
  tenant queue-wait / claim-latency / steal-latency distributions,
  lease churn, per-worker busy seconds and occupancy — the offline
  audit of the live ``s2c_sched_*`` exposition family;
* a **critical-path report** (``--report``): per job the end-to-end
  queue -> claim -> decode -> dispatch -> tail -> commit decomposition
  (phase splits joined from job manifests via ``--manifests GLOB``),
  aggregated into the fleet "where does the wall go" table.

``--leg`` runs the self-contained campaign harness instead (step 15 of
tools/tpu_campaign.sh): a 2-worker journaled queue with one mid-queue
SIGKILL cycle, then assembles the journal + surviving worker traces,
asserts trace validity (flight.validate: >=1 per-job track, zero
negative durations, zero orphans), sched-metric presence including a
measured steal gap within 2 x lease TTL, and byte identity against a
chaos-free baseline — one JSONL row per check plus a summary row
(committed cpu-fallback artifact:
campaign/fleet_trace_r06_cpufallback.jsonl).

Usage:
  python tools/fleet_trace.py --journal DIR [--worker-traces GLOB]
         [--manifests GLOB] [--out trace.json] [--report]
  python tools/fleet_trace.py --leg [--jobs 3] [--reads 8000]
         [--lease-ttl 2.5] [--out FILE.jsonl] [--trace-out FILE.json]
"""

import argparse
import glob as globmod
import json
import os
import shutil
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(1, os.path.dirname(os.path.abspath(__file__)))

from fleet_soak import (journal_events, log, sha_dir,  # noqa: E402
                        wait_for_inflight, worker_cmd)


def load_worker_traces(patterns):
    """Parsed --trace-out blobs (dicts) from glob patterns; files
    without the ``s2c`` wall anchor still load (the assembler skips
    them with their absence visible in the summary)."""
    blobs = []
    for pat in patterns or ():
        for p in sorted(globmod.glob(pat)):
            try:
                with open(p, encoding="utf-8") as fh:
                    blob = json.load(fh)
            except (OSError, ValueError) as exc:
                log(f"[fleet_trace] skipping unreadable trace {p}: "
                    f"{exc}")
                continue
            blob["_path"] = p
            blobs.append(blob)
    return blobs


def load_phase_maps(patterns):
    """trace_id -> ``phase/<p>_sec`` dict, joined from job manifests
    (their ``lifecycle.trace_id`` + ``phases`` sections)."""
    out = {}
    for pat in patterns or ():
        for p in sorted(globmod.glob(pat)):
            try:
                with open(p, encoding="utf-8") as fh:
                    man = json.load(fh)
            except (OSError, ValueError):
                continue
            tid = (man.get("lifecycle") or {}).get("trace_id")
            if tid and man.get("phases"):
                out[tid] = man["phases"]
    return out


def assemble_journal(jdir, worker_trace_globs=(), manifest_globs=()):
    """(jobs, chrome_events, sched, report) for one journal dir."""
    from sam2consensus_tpu.observability import flight

    evs = journal_events(jdir)
    if not evs:
        raise SystemExit(f"no journal events under {jdir}")
    jobs = flight.assemble(evs)
    traces = load_worker_traces(worker_trace_globs)
    events = flight.chrome_events(jobs, worker_traces=traces)
    sched = flight.sched_metrics(jobs)
    report = flight.wall_report(jobs,
                                load_phase_maps(manifest_globs))
    return jobs, events, sched, report


def write_trace(path, events, sched):
    blob = {"traceEvents": events, "displayTimeUnit": "ms",
            "s2c": {"kind": "fleet_trace", "sched": sched}}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(blob, fh, ensure_ascii=False)
        fh.write("\n")


def print_report(report, sched, file=sys.stdout):
    print("fleet critical path — where does the wall go", file=file)
    total = report["total_sec"]
    for bucket, sec in report["totals_sec"].items():
        pct = report["pct"][bucket]
        bar = "#" * int(round(pct / 2))
        print(f"  {bucket:>10}  {sec:10.3f}s  {pct:6.2f}%  {bar}",
              file=file)
    print(f"  {'total':>10}  {total:10.3f}s", file=file)
    print(f"workers ({len(sched['workers'])}):", file=file)
    for w, info in sorted(sched["workers"].items()):
        print(f"  {w:>10}  busy {info['busy_sec']:.3f}s  "
              f"occupancy {info['occupancy']:.1%}  "
              f"jobs {info['jobs']}", file=file)
    print(f"lease churn: {sched['lease_churn']}", file=file)


# =========================================================================
# --leg: the campaign harness (2 workers, one SIGKILL, assemble+assert)
# =========================================================================
def run_leg(args):
    import tempfile

    from sam2consensus_tpu.observability import flight
    from sam2consensus_tpu.serve.journal import JobJournal
    from sam2consensus_tpu.utils.simulate import SimSpec, simulate

    work = args.workdir or tempfile.mkdtemp(prefix="s2c_ftrace_")
    os.makedirs(work, exist_ok=True)
    log(f"[fleet_trace] leg workdir {work}")

    inputs = []
    for k in range(args.jobs):
        spec = SimSpec(n_contigs=1, contig_len=args.contig_len,
                       n_reads=args.reads, read_len=args.read_len,
                       contig_len_jitter=0.0, seed=7600 + k,
                       contig_prefix=f"ft{k:02d}_")
        p = os.path.join(work, f"job{k}.sam")
        with open(p, "w") as fh:
            fh.write(simulate(spec))
        inputs.append(p)

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["S2C_JIT_CACHE"] = os.path.join(work, "_jit_cache")

    # chaos-free single-worker baseline: the byte-identity oracle
    # (the flight recorder is passive — recording must not change
    # output bytes)
    base_out = os.path.join(work, "out_base")
    r = subprocess.run(worker_cmd(inputs, base_out,
                                  os.path.join(work, "j_base"),
                                  "base0", args.lease_ttl),
                       env=env, capture_output=True, text=True,
                       timeout=args.per_process_timeout)
    if r.returncode != 0:
        log(f"[fleet_trace] baseline failed rc={r.returncode}:\n"
            f"{r.stderr[-2000:]}")
        return 2
    want = sha_dir(base_out)

    # 2-worker kill cycle, per-worker trace artifacts via the
    # env-derived per-job suffixing (S2C_TRACE_OUT -> <base>.jobN)
    outdir = os.path.join(work, "out_fleet")
    jdir = os.path.join(work, "j_fleet")
    procs = {}
    for w in ("ft0", "ft1"):
        wenv = dict(env)
        wenv["S2C_TRACE_OUT"] = os.path.join(work, f"trace_{w}")
        procs[w] = subprocess.Popen(
            worker_cmd(inputs, outdir, jdir, w, args.lease_ttl),
            env=wenv, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
    deadline = time.monotonic() + args.per_process_timeout
    victim, vkey = wait_for_inflight(jdir, deadline)
    t_signal = None
    if victim in procs:
        t_signal = time.time()
        procs[victim].send_signal(signal.SIGKILL)
        log(f"[fleet_trace] killed {victim} holding {vkey}")
    rc = 0
    for w, pr in procs.items():
        try:
            pr.wait(timeout=args.per_process_timeout)
        except subprocess.TimeoutExpired:
            pr.kill()
            pr.wait(timeout=30)
            rc = rc or -1
        if w != victim:
            rc = rc or pr.returncode

    # -- assemble + assert ---------------------------------------------
    trace_glob = os.path.join(work, "trace_*")
    jobs, events, sched, report = assemble_journal(
        jdir, worker_trace_globs=[trace_glob])
    errors = flight.validate(events)
    trace_out = args.trace_out or os.path.join(work,
                                               "fleet_trace.json")
    write_trace(trace_out, events, sched)
    log(f"[fleet_trace] wrote {trace_out} ({len(events)} events)")

    audit = JobJournal(jdir).audit()
    got = sha_dir(outdir) if os.path.isdir(outdir) else {}
    steals = [jl.steal_latency_sec for jl in jobs.values()
              if jl.steal_latency_sec is not None]
    bound = 2 * args.lease_ttl
    qw = [v for t in sched["per_tenant"].values()
          for v in t["queue_wait_sec"]]
    # the victim may have committed the watched job in the scan ->
    # signal gap (same degenerate case fleet_soak tolerates)
    signal_late = t_signal is not None and not steals and any(
        e.get("ev") == "committed" and e.get("key") == vkey
        and e.get("worker") == victim for e in journal_events(jdir))
    checks = {
        "rc_zero": rc == 0,
        "trace_valid": not errors,
        "per_job_tracks": len(jobs) >= args.jobs,
        "sched_queue_wait_present": len(qw) >= args.jobs,
        "steal_measured": bool(steals) or signal_late,
        "steal_within_bound": (max(steals) <= bound) if steals
        else signal_late,
        "identical": got == want,
        "lost_zero": not audit["lost"],
        "duplicated_zero": not audit["duplicated"],
    }
    ok = all(checks.values())
    if errors:
        log("[fleet_trace] validation errors: "
            + "; ".join(errors[:10]))
    rows = [{"mode": "leg_check", "check": k, "ok": v}
            for k, v in checks.items()]
    rows.append({
        "mode": "summary", "ok": ok,
        "jobs": args.jobs, "workers": 2, "reads": args.reads,
        "lease_ttl_sec": args.lease_ttl,
        "events": len(events),
        "per_job_tracks": len(jobs),
        "validation_errors": len(errors),
        "victim": victim, "signal_late": signal_late,
        "max_steal_sec": round(max(steals), 3) if steals else None,
        "steal_bound_sec": bound,
        "queue_wait_p50_sec": round(
            sorted(qw)[len(qw) // 2], 3) if qw else None,
        "lease_churn": sched["lease_churn"],
        "occupancy": {w: i["occupancy"]
                      for w, i in sched["workers"].items()},
        "identical_all": checks["identical"],
        "lost_total": len(audit["lost"]),
        "duplicated_total": len(audit["duplicated"]),
        "failures": 0 if ok else 1,
        "host_cores": os.cpu_count(),
        "platform": os.environ.get("JAX_PLATFORMS", ""),
    })
    blob = "\n".join(json.dumps(r) for r in rows) + "\n"
    if args.out and args.out != "-":
        with open(args.out, "w") as fh:
            fh.write(blob)
        log(f"[fleet_trace] wrote {args.out}")
    else:
        # "-"/unset: rows to stdout (tpu_campaign.sh's run_step
        # captures stdout as the committed artifact)
        sys.stdout.write(blob)
    if not args.workdir:
        shutil.rmtree(work, ignore_errors=True)
    return 0 if ok else 1


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--journal", default=None,
                    help="journal directory to assemble")
    ap.add_argument("--worker-traces", action="append", default=[],
                    help="glob of per-worker --trace-out JSONs "
                         "(repeatable)")
    ap.add_argument("--manifests", action="append", default=[],
                    help="glob of job manifest JSONs for the "
                         "critical-path phase split (repeatable)")
    ap.add_argument("--out", default=None,
                    help="trace JSON destination (assembler mode) / "
                         "JSONL destination (--leg)")
    ap.add_argument("--report", action="store_true",
                    help="print the fleet critical-path report")
    ap.add_argument("--leg", action="store_true",
                    help="run the campaign harness (2 workers, one "
                         "kill, assemble + assert)")
    ap.add_argument("--jobs", type=int, default=3)
    ap.add_argument("--reads", type=int, default=8000)
    ap.add_argument("--contig-len", type=int, default=5000)
    ap.add_argument("--read-len", type=int, default=100)
    ap.add_argument("--lease-ttl", type=float, default=2.5)
    ap.add_argument("--per-process-timeout", type=float, default=600.0)
    ap.add_argument("--workdir", default=None,
                    help="leg scratch dir (default: a fresh tempdir)")
    ap.add_argument("--trace-out", default=None,
                    help="leg: where to keep the assembled trace")
    args = ap.parse_args(argv)

    if args.leg:
        return run_leg(args)
    if not args.journal:
        ap.error("--journal DIR is required (or use --leg)")
    from sam2consensus_tpu.observability import flight

    jobs, events, sched, report = assemble_journal(
        args.journal, args.worker_traces, args.manifests)
    errors = flight.validate(events)
    if args.out:
        write_trace(args.out, events, sched)
        log(f"[fleet_trace] wrote {args.out} ({len(events)} events, "
            f"{len(jobs)} job track(s))")
    if args.report:
        print_report(report, sched)
    else:
        print(json.dumps({"jobs": len(jobs), "events": len(events),
                          "validation_errors": errors,
                          "sched": sched}, indent=1))
    return 0 if not errors else 1


if __name__ == "__main__":
    sys.exit(main())
