#!/usr/bin/env python3
"""Randomized cpu-vs-jax byte-identity fuzz over simulated workloads.

Beyond the fixed differential corpus (tests/test_differential.py), this
sweeps random SimSpecs x config knobs — threshold lists including 1.0 /
0.0001 / 1/3 / 0.9999999, min_depth, fill characters, maxdel including
0, strict and permissive modes, heavy indel rates, tiny and many
contigs — and asserts byte-identical FASTA output between the oracle
and the jax backend for every runnable draw.  Round-4 record: 80/80
clean (the new SIMD vote, direct/shadow fused counting, native
insertion tail, and segmented contig sums all in the loop).

Usage: python tools/fuzz_differential.py [n_trials] [seed]
"""

import io
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sam2consensus_tpu.utils.platform import pin_platform_from_env  # noqa: E402

pin_platform_from_env()

from sam2consensus_tpu.backends.cpu import CpuBackend            # noqa: E402
from sam2consensus_tpu.backends.jax_backend import JaxBackend    # noqa: E402
from sam2consensus_tpu.config import RunConfig                   # noqa: E402
from sam2consensus_tpu.io.fasta import render_file               # noqa: E402
from sam2consensus_tpu.io.sam import iter_records, read_header   # noqa: E402
from sam2consensus_tpu.utils.simulate import SimSpec, simulate   # noqa: E402


def main() -> int:
    n_trials = int(sys.argv[1]) if len(sys.argv) > 1 else 80
    seed = int(sys.argv[2]) if len(sys.argv) > 2 else 4242
    rng = random.Random(seed)
    fails = ran = 0
    for trial in range(n_trials):
        spec = SimSpec(
            n_contigs=rng.choice([1, 2, 3, 7, 40]),
            contig_len=rng.choice([5, 20, 60, 150, 400, 1200]),
            n_reads=rng.choice([0, 1, 10, 80, 400]),
            read_len=rng.choice([4, 8, 12, 30, 60]),
            ins_read_rate=rng.choice([0.0, 0.1, 0.5]),
            del_read_rate=rng.choice([0.0, 0.1, 0.5]),
            seed=rng.randrange(10 ** 6))
        kw = dict(
            prefix="f", shards=1,
            thresholds=rng.choice(
                [[0.25], [0.5, 0.75], [1.0], [0.0001],
                 [1.0 / 3.0, 0.9999999], [0.25, 0.5, 0.75, 1.0]]),
            min_depth=rng.choice([1, 2, 5]),
            fill=rng.choice(["-", "N", "?"]),
            maxdel=rng.choice([None, 0, 2, 150]),
            strict=rng.choice([False, True]))
        try:
            text = simulate(spec)
        except ValueError:
            continue                  # simulator domain limit, not a run
        ran += 1
        try:
            cfg = RunConfig(**kw)

            def run(backend):
                handle = io.StringIO(text)
                contigs, _n, first = read_header(handle)
                res = backend.run(contigs, iter_records(handle, first),
                                  cfg)
                return {n: render_file(r, 0)
                        for n, r in res.fastas.items()}

            if run(CpuBackend()) != run(JaxBackend()):
                fails += 1
                print(f"MISMATCH trial {trial}: spec={spec} kw={kw}",
                      file=sys.stderr)
        except Exception as exc:      # noqa: BLE001 - report and continue
            fails += 1
            print(f"ERROR trial {trial}: {type(exc).__name__}: {exc} "
                  f"spec={spec} kw={kw}", file=sys.stderr)
        if trial % 20 == 19:
            print(f"... {trial + 1}/{n_trials}, ran={ran}, fails={fails}",
                  file=sys.stderr, flush=True)
    print(f"FUZZ RESULT: ran={ran} "
          + ("CLEAN" if fails == 0 else f"{fails} FAILURES"))
    return 1 if fails else 0


if __name__ == "__main__":
    sys.exit(main())
