#!/usr/bin/env python3
"""Randomized cpu-vs-jax byte-identity fuzz over simulated workloads.

Beyond the fixed differential corpus (tests/test_differential.py), this
sweeps random SimSpecs x config knobs — threshold lists including 1.0 /
0.0001 / 1/3 / 0.9999999, min_depth, fill characters, maxdel including
0, strict and permissive modes, heavy indel rates, tiny and many
contigs — and asserts byte-identical FASTA output between the oracle
and the jax backend for every runnable draw.  ~1 in 4 trials runs
SHARDED on the 8-virtual-device mesh with a random dp/sp/dpsp layout.

Round 5 adds the axes the round-4 fuzzer skipped (verdict r4 #8), each
still differential vs the oracle on the FULL input:

* ``crash_resume`` — the jax run is killed mid-stream by an injected
  I/O fault after a random number of bytes, leaving a mid-input
  checkpoint (random ``checkpoint_every``); the rerun resumes from the
  byte-offset and must land byte-identical;
* ``incremental`` — the read body is split into 2-3 shard files
  absorbed one checkpointed ``--incremental`` run at a time (with a
  random duplicate re-run of an absorbed shard: must be a no-op);
* ``cli`` — whole-directory byte identity through the REAL CLI
  (``cli.main``), drawing gzip inputs, ``--py2-compat`` with an
  explicit ``-d`` (quirk-1 boundary), wrapping, and fill chars;
* ``corrupt`` — malformed records (unknown refname / out-of-bounds
  POS / out-of-alphabet bases) spliced into the body; permissive mode
  must skip the same records (count parity) and emit identical bytes,
  strict mode must raise the oracle's exception type.

Round-4 records: ~930 clean trials across the base + sharded draws.

Usage: python tools/fuzz_differential.py [n_trials] [seed]
"""

import gzip
import io
import os
import random
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sam2consensus_tpu.utils.platform import pin_platform_from_env  # noqa: E402

pin_platform_from_env()

from sam2consensus_tpu.backends.cpu import CpuBackend            # noqa: E402
from sam2consensus_tpu.backends.jax_backend import JaxBackend    # noqa: E402
from sam2consensus_tpu.config import RunConfig                   # noqa: E402
from sam2consensus_tpu.io.fasta import render_file               # noqa: E402
from sam2consensus_tpu.io.sam import iter_records, read_header   # noqa: E402
from sam2consensus_tpu.utils.simulate import SimSpec, simulate   # noqa: E402


def _n_devices() -> int:
    import jax

    try:
        return len(jax.devices())
    except RuntimeError:
        return 1


def _oracle(text: str, cfg: RunConfig):
    handle = io.StringIO(text)
    contigs, _n, first = read_header(handle)
    res = CpuBackend().run(contigs, iter_records(handle, first), cfg)
    return {n: render_file(r, 0) for n, r in res.fastas.items()}, res.stats


class _CrashingBytes(io.BytesIO):
    """File handle that fails after ``limit`` bytes have been read —
    the fuzzer's mid-stream crash injector (covers both the python
    line reader and the native block reader)."""

    def __init__(self, data: bytes, limit: int):
        super().__init__(data)
        self._limit = limit

    def _check(self):
        if self.tell() >= self._limit:
            raise RuntimeError("injected mid-stream crash")

    def read(self, *a):
        self._check()
        return super().read(*a)

    def readline(self, *a):
        self._check()
        return super().readline(*a)


def _jax_file_run(path: str, cfg: RunConfig, handle=None):
    """Run the jax backend from a file (the CLI's decode path)."""
    from sam2consensus_tpu.io.sam import ReadStream, opener

    h = handle if handle is not None else opener(path, binary=True)
    contigs, _n, first = read_header(h)
    res = JaxBackend().run(contigs, ReadStream(h, first), cfg)
    h.close()
    return {n: render_file(r, 0) for n, r in res.fastas.items()}, res.stats


def _trial_crash_resume(rng, text, kw, tmp) -> str:
    """Crash mid-stream, resume from the checkpoint; '' or failure."""
    data = text.encode()
    path = os.path.join(tmp, "in.sam")
    with open(path, "wb") as fh:
        fh.write(data)
    ckdir = os.path.join(tmp, "ck")
    kw = dict(kw, strict=True, checkpoint_dir=ckdir,
              checkpoint_every=rng.choice([1, 3, 17]))
    cfg = RunConfig(**kw)
    want, _ = _oracle(text, cfg)
    # any crash point is a valid trial: before the header completes the
    # run dies with no checkpoint (fresh restart), mid-body it leaves a
    # partial checkpoint (offset resume), at EOF a near-complete one
    limit = rng.randrange(1, len(data) + 1)
    try:
        _jax_file_run(path, cfg, handle=_CrashingBytes(data, limit))
    except Exception as exc:  # noqa: BLE001
        if "injected" not in str(exc):
            return f"crash run died wrong: {type(exc).__name__}: {exc}"
    got, stats = _jax_file_run(path, cfg)
    if got != want:
        return "crash_resume byte mismatch"
    if os.path.exists(os.path.join(ckdir, "sam2consensus_ckpt.npz")):
        return "completed run left its checkpoint behind"
    return ""


def _trial_incremental(rng, text, kw, tmp) -> str:
    """Absorb the input as 2-3 incremental shards; '' or failure."""
    lines = text.splitlines(keepends=True)
    head = [ln for ln in lines if ln.startswith("@")]
    body = [ln for ln in lines if not ln.startswith("@")]
    n_shards = rng.choice([2, 3])
    cuts = sorted(rng.sample(range(len(body) + 1), n_shards - 1)) \
        if len(body) else []
    parts = []
    prev = 0
    for c in cuts + [len(body)]:
        parts.append(body[prev:c])
        prev = c
    ckdir = os.path.join(tmp, "ck")
    kw = dict(kw, strict=True, incremental=True, checkpoint_dir=ckdir)
    cfg_full = RunConfig(**{k: v for k, v in kw.items()
                            if k not in ("incremental", "checkpoint_dir",
                                         "source_id")})
    want, _ = _oracle(text, cfg_full)
    got = None
    paths = []
    for i, part in enumerate(parts):
        path = os.path.join(tmp, f"shard{i}.sam")
        with open(path, "w") as fh:
            fh.write("".join(head + part))
        paths.append(path)
    for i, path in enumerate(paths):
        got, _ = _jax_file_run(path, RunConfig(**dict(kw, source_id=path)))
    if rng.random() < 0.5 and paths:
        # idempotency: re-running an absorbed shard adds nothing
        dup = rng.choice(paths)
        got, stats = _jax_file_run(dup, RunConfig(**dict(kw,
                                                         source_id=dup)))
        if stats.extra.get("incremental_duplicate") != dup:
            return "duplicate shard not detected"
    if got != want:
        return "incremental byte mismatch"
    return ""


def _trial_cli(rng, text, kw, tmp) -> str:
    """Whole-directory identity through cli.main; '' or failure."""
    from sam2consensus_tpu import cli

    gz = rng.random() < 0.5
    path = os.path.join(tmp, "in.sam" + (".gz" if gz else ""))
    if gz:
        with gzip.open(path, "wt") as fh:
            fh.write(text)
    else:
        with open(path, "w") as fh:
            fh.write(text)
    argv = ["-i", path, "-c", ",".join(str(t) for t in kw["thresholds"]),
            "-m", str(kw["min_depth"]), "-f", kw["fill"]]
    if rng.random() < 0.5:
        argv += ["-n", str(rng.choice([1, 7, 60]))]
    if kw["maxdel"] is not None:
        argv += ["-d", str(kw["maxdel"])]
        if rng.random() < 0.5:
            # quirk-1 boundary: --py2-compat + explicit -d disables the
            # deletion gate exactly like the reference's str/int compare
            argv += ["--py2-compat"]
    out_cpu = os.path.join(tmp, "out_cpu")
    out_jax = os.path.join(tmp, "out_jax")
    from contextlib import redirect_stdout

    with redirect_stdout(io.StringIO()):
        rc1 = cli.main(argv + ["-o", out_cpu, "--backend", "cpu"])
        rc2 = cli.main(argv + ["-o", out_jax, "--backend", "jax"])
    if rc1 != 0 or rc2 != 0:
        return f"cli rc cpu={rc1} jax={rc2}"
    names_c = sorted(os.listdir(out_cpu))
    names_j = sorted(os.listdir(out_jax))
    if names_c != names_j:
        return f"cli file sets differ: {names_c} vs {names_j}"
    for n in names_c:
        with open(os.path.join(out_cpu, n), "rb") as a, \
                open(os.path.join(out_jax, n), "rb") as b:
            if a.read() != b.read():
                return f"cli byte mismatch in {n}"
    return ""


def _corrupt_body(rng, text: str) -> str:
    """Splice malformed records into the body (oracle-typed errors)."""
    lines = text.splitlines(keepends=True)
    body_idx = [i for i, ln in enumerate(lines)
                if not ln.startswith("@")]
    bad = []
    refname = None
    for ln in lines:
        if ln.startswith("@SQ"):
            for f in ln.split("\t"):
                if f.startswith("SN:"):
                    refname = f[3:]
    if refname is None:
        return text
    bad.append(f"r1\t0\tNOSUCHREF\t1\t60\t4M\t*\t0\t0\tACGT\t*\n")
    bad.append(f"r2\t0\t{refname}\t999999999\t60\t4M\t*\t0\t0\tACGT\t*\n")
    bad.append(f"r3\t0\t{refname}\t1\t60\t4M\t*\t0\t0\tacgt\t*\n")
    for b in rng.sample(bad, rng.randrange(1, len(bad) + 1)):
        pos = rng.choice(body_idx) if body_idx else len(lines)
        lines.insert(pos, b)
    return "".join(lines)


def _trial_corrupt(rng, text, kw) -> str:
    """Permissive skip parity / strict error-type parity; '' or fail."""
    bad_text = _corrupt_body(rng, text)
    if bad_text == text:
        return ""
    kw = dict(kw, strict=False)
    cfg = RunConfig(**kw)
    want, st_cpu = _oracle(bad_text, cfg)
    handle = io.StringIO(bad_text)
    contigs, _n, first = read_header(handle)
    res = JaxBackend().run(contigs, iter_records(handle, first), cfg)
    got = {n: render_file(r, 0) for n, r in res.fastas.items()}
    if got != want:
        return "permissive byte mismatch"
    if res.stats.reads_skipped != st_cpu.reads_skipped:
        return (f"skip parity: jax {res.stats.reads_skipped} vs cpu "
                f"{st_cpu.reads_skipped}")
    # strict: both must raise the same exception type
    cfg_s = RunConfig(**dict(kw, strict=True))
    errs = []
    for backend in (CpuBackend(), JaxBackend()):
        h = io.StringIO(bad_text)
        contigs, _n, first = read_header(h)
        try:
            backend.run(contigs, iter_records(h, first), cfg_s)
            errs.append(None)
        except Exception as exc:  # noqa: BLE001
            errs.append(type(exc).__name__)
    if errs[0] != errs[1]:
        return f"strict error-type parity: cpu {errs[0]} vs jax {errs[1]}"
    return ""


def main() -> int:
    n_trials = int(sys.argv[1]) if len(sys.argv) > 1 else 80
    seed = int(sys.argv[2]) if len(sys.argv) > 2 else 4242
    rng = random.Random(seed)
    fails = ran = 0
    flavors: dict = {}
    for trial in range(n_trials):
        spec = SimSpec(
            n_contigs=rng.choice([1, 2, 3, 7, 40]),
            contig_len=rng.choice([5, 20, 60, 150, 400, 1200]),
            n_reads=rng.choice([0, 1, 10, 80, 400]),
            read_len=rng.choice([4, 8, 12, 30, 60]),
            ins_read_rate=rng.choice([0.0, 0.1, 0.5]),
            del_read_rate=rng.choice([0.0, 0.1, 0.5]),
            seed=rng.randrange(10 ** 6))
        # ~1 in 4 trials runs SHARDED on the virtual mesh, random layout:
        # dp (scatter + reduce-scatter), sp (routing + halo), dpsp
        # (product mode) — the odd-halo pack_nibbles crash only lived in
        # shard-mode x genome-shape combinations no fixed test drew.
        # Clamp draws to the devices actually up, so a standalone run
        # without --xla_force_host_platform_device_count still fuzzes
        # (single-device only) instead of tripping make_mesh.
        shards, shard_mode = 1, "auto"
        shard_pool = [s for s in (2, 4, 8) if s <= _n_devices()]
        if shard_pool and rng.random() < 0.25:
            shards = rng.choice(shard_pool)
            # dpsp needs a true 2-D mesh (factor_mesh(2) is 2x1 -> refused)
            shard_mode = rng.choice(
                ["dp", "sp", "dpsp"] if shards >= 4 else ["dp", "sp"])
        kw = dict(
            prefix="f", shards=shards, shard_mode=shard_mode,
            thresholds=rng.choice(
                [[0.25], [0.5, 0.75], [1.0], [0.0001],
                 [1.0 / 3.0, 0.9999999], [0.25, 0.5, 0.75, 1.0]]),
            min_depth=rng.choice([1, 2, 5]),
            fill=rng.choice(["-", "N", "?"]),
            maxdel=rng.choice([None, 0, 2, 150]),
            # device-kernel draws: the Pallas insertion kernel (fused
            # in-kernel vote) runs in interpret mode here, and the
            # pileup kernels ride their interpret/CPU twins — tiny
            # inputs keep that affordable
            ins_kernel=rng.choice(["auto", "scatter", "pallas"]),
            pileup=rng.choice(["auto", "auto", "scatter", "pallas",
                               "mxu"]),
            strict=rng.choice([False, True]))
        try:
            text = simulate(spec)
        except ValueError:
            continue                  # simulator domain limit, not a run
        ran += 1
        # round-5 flavors (verdict r4 #8): most trials keep the base
        # in-memory differential; the rest draw the aux-subsystem axes
        flavor = rng.choices(
            ["base", "crash_resume", "incremental", "cli", "corrupt"],
            weights=[55, 12, 12, 11, 10])[0]
        flavors[flavor] = flavors.get(flavor, 0) + 1
        try:
            fail_msg = ""
            if flavor == "base":
                cfg = RunConfig(**kw)

                def run(backend):
                    handle = io.StringIO(text)
                    contigs, _n, first = read_header(handle)
                    res = backend.run(contigs,
                                      iter_records(handle, first), cfg)
                    return {n: render_file(r, 0)
                            for n, r in res.fastas.items()}

                if run(CpuBackend()) != run(JaxBackend()):
                    fail_msg = "byte mismatch"
            elif flavor == "corrupt":
                fail_msg = _trial_corrupt(rng, text, kw)
            else:
                with tempfile.TemporaryDirectory() as tmp:
                    if flavor == "crash_resume":
                        fail_msg = _trial_crash_resume(rng, text, kw, tmp)
                    elif flavor == "incremental":
                        fail_msg = _trial_incremental(rng, text, kw, tmp)
                    else:
                        fail_msg = _trial_cli(rng, text, kw, tmp)
            if fail_msg:
                fails += 1
                print(f"FAIL trial {trial} [{flavor}]: {fail_msg} "
                      f"spec={spec} kw={kw}", file=sys.stderr)
        except Exception as exc:      # noqa: BLE001 - report and continue
            fails += 1
            print(f"ERROR trial {trial} [{flavor}]: "
                  f"{type(exc).__name__}: {exc} spec={spec} kw={kw}",
                  file=sys.stderr)
        if trial % 20 == 19:
            print(f"... {trial + 1}/{n_trials}, ran={ran}, fails={fails}",
                  file=sys.stderr, flush=True)
    print(f"FUZZ RESULT: ran={ran} flavors={flavors} "
          + ("CLEAN" if fails == 0 else f"{fails} FAILURES"))
    return 1 if fails else 0


if __name__ == "__main__":
    sys.exit(main())
