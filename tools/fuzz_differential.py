#!/usr/bin/env python3
"""Randomized cpu-vs-jax byte-identity fuzz over simulated workloads.

Beyond the fixed differential corpus (tests/test_differential.py), this
sweeps random SimSpecs x config knobs — threshold lists including 1.0 /
0.0001 / 1/3 / 0.9999999, min_depth, fill characters, maxdel including
0, strict and permissive modes, heavy indel rates, tiny and many
contigs — and asserts byte-identical FASTA output between the oracle
and the jax backend for every runnable draw.  ~1 in 4 trials runs
SHARDED on the 8-virtual-device mesh with a random dp/sp/dpsp layout.
Round-4 records: 80/80 clean mid-round; 200/200 clean after the
late-round kernel pass (SIMD shadow merge, banked gate, scan-free
placement); 200/200 + 400/400 clean WITH sharded draws after the
odd-halo pack_nibbles fix (~930 clean trials total this round).

Usage: python tools/fuzz_differential.py [n_trials] [seed]
"""

import io
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sam2consensus_tpu.utils.platform import pin_platform_from_env  # noqa: E402

pin_platform_from_env()

from sam2consensus_tpu.backends.cpu import CpuBackend            # noqa: E402
from sam2consensus_tpu.backends.jax_backend import JaxBackend    # noqa: E402
from sam2consensus_tpu.config import RunConfig                   # noqa: E402
from sam2consensus_tpu.io.fasta import render_file               # noqa: E402
from sam2consensus_tpu.io.sam import iter_records, read_header   # noqa: E402
from sam2consensus_tpu.utils.simulate import SimSpec, simulate   # noqa: E402


def _n_devices() -> int:
    import jax

    try:
        return len(jax.devices())
    except RuntimeError:
        return 1


def main() -> int:
    n_trials = int(sys.argv[1]) if len(sys.argv) > 1 else 80
    seed = int(sys.argv[2]) if len(sys.argv) > 2 else 4242
    rng = random.Random(seed)
    fails = ran = 0
    for trial in range(n_trials):
        spec = SimSpec(
            n_contigs=rng.choice([1, 2, 3, 7, 40]),
            contig_len=rng.choice([5, 20, 60, 150, 400, 1200]),
            n_reads=rng.choice([0, 1, 10, 80, 400]),
            read_len=rng.choice([4, 8, 12, 30, 60]),
            ins_read_rate=rng.choice([0.0, 0.1, 0.5]),
            del_read_rate=rng.choice([0.0, 0.1, 0.5]),
            seed=rng.randrange(10 ** 6))
        # ~1 in 4 trials runs SHARDED on the virtual mesh, random layout:
        # dp (scatter + reduce-scatter), sp (routing + halo), dpsp
        # (product mode) — the odd-halo pack_nibbles crash only lived in
        # shard-mode x genome-shape combinations no fixed test drew.
        # Clamp draws to the devices actually up, so a standalone run
        # without --xla_force_host_platform_device_count still fuzzes
        # (single-device only) instead of tripping make_mesh.
        shards, shard_mode = 1, "auto"
        shard_pool = [s for s in (2, 4, 8) if s <= _n_devices()]
        if shard_pool and rng.random() < 0.25:
            shards = rng.choice(shard_pool)
            # dpsp needs a true 2-D mesh (factor_mesh(2) is 2x1 -> refused)
            shard_mode = rng.choice(
                ["dp", "sp", "dpsp"] if shards >= 4 else ["dp", "sp"])
        kw = dict(
            prefix="f", shards=shards, shard_mode=shard_mode,
            thresholds=rng.choice(
                [[0.25], [0.5, 0.75], [1.0], [0.0001],
                 [1.0 / 3.0, 0.9999999], [0.25, 0.5, 0.75, 1.0]]),
            min_depth=rng.choice([1, 2, 5]),
            fill=rng.choice(["-", "N", "?"]),
            maxdel=rng.choice([None, 0, 2, 150]),
            strict=rng.choice([False, True]))
        try:
            text = simulate(spec)
        except ValueError:
            continue                  # simulator domain limit, not a run
        ran += 1
        try:
            cfg = RunConfig(**kw)

            def run(backend):
                handle = io.StringIO(text)
                contigs, _n, first = read_header(handle)
                res = backend.run(contigs, iter_records(handle, first),
                                  cfg)
                return {n: render_file(r, 0)
                        for n, r in res.fastas.items()}

            if run(CpuBackend()) != run(JaxBackend()):
                fails += 1
                print(f"MISMATCH trial {trial}: spec={spec} kw={kw}",
                      file=sys.stderr)
        except Exception as exc:      # noqa: BLE001 - report and continue
            fails += 1
            print(f"ERROR trial {trial}: {type(exc).__name__}: {exc} "
                  f"spec={spec} kw={kw}", file=sys.stderr)
        if trial % 20 == 19:
            print(f"... {trial + 1}/{n_trials}, ran={ran}, fails={fails}",
                  file=sys.stderr, flush=True)
    print(f"FUZZ RESULT: ran={ran} "
          + ("CLEAN" if fails == 0 else f"{fails} FAILURES"))
    return 1 if fails else 0


if __name__ == "__main__":
    sys.exit(main())
