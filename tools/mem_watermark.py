#!/usr/bin/env python3
"""Memory-watermark bench leg: peak host+device bytes per config, JSONL.

The bench trajectory measures seconds; this leg measures *residency* —
one row per config carrying the memory plane's per-family peak bytes,
the process peak RSS, device peaks where the backend exposes them, and
the ``capacity`` ledger decision's predicted-vs-measured residual
(observability/memplane.py).  Each config runs in its OWN subprocess:
``ru_maxrss`` is a process-lifetime high-water mark, so in-process
sequencing would make every config inherit its predecessors' peak —
the exact distortion this tool exists to avoid.

Configs are deliberately CHUNK-FILLING (``chunk_reads`` below the read
count) with the device pileup pinned, so the staged-slab geometry the
capacity model prices is the geometry that actually allocates and the
residual lands inside the default drift band — the committed artifact
(``campaign/mem_watermark_r06_cpufallback.jsonl``) is what keeps the
model honest (the model's residual on under-filled interactive runs is
informational headroom by design).

Usage:
  python tools/mem_watermark.py --out -                 # JSONL to stdout
  python tools/mem_watermark.py --out mem.jsonl --configs phix_8k
  python tools/regress_check.py --jsonl mem.jsonl \
      --group-by config --value peak_rss_mb --lower-is-better

Wired as the idempotent ``mem_watermark`` campaign step
(tools/tpu_campaign.sh); gated alongside ``jax_sec`` by
tools/regress_check.py (``peak_rss_mb`` rides the default bench-series
metric set too).
"""

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: (name, sim kwargs, run kwargs) — chunk-filling shapes, device pileup
CONFIGS = {
    "phix_8k": (
        dict(n_contigs=1, contig_len=5386, n_reads=8000, read_len=100,
             seed=101, contig_prefix="phiX"),
        dict(thresholds=[0.25], chunk_reads=2048, pileup="scatter")),
    "target_capture_16k": (
        dict(n_contigs=350, contig_len=1200, n_reads=16000,
             read_len=100, seed=202, contig_prefix="gene"),
        dict(thresholds=[0.25], chunk_reads=4096, pileup="scatter")),
    "multithreshold_8k": (
        dict(n_contigs=1, contig_len=5386, n_reads=8000, read_len=100,
             seed=101, contig_prefix="phiX"),
        dict(thresholds=[0.25, 0.5, 0.75], chunk_reads=2048,
             pileup="scatter")),
}


def run_one(name: str) -> dict:
    """Run ONE config in this process and print its row (the subprocess
    entry — fresh ru_maxrss, fresh jit cache, fresh memory plane)."""
    import tempfile

    from sam2consensus_tpu.backends.jax_backend import JaxBackend
    from sam2consensus_tpu.config import RunConfig
    from sam2consensus_tpu.formats import open_alignment_input
    from sam2consensus_tpu.utils.simulate import SimSpec, simulate

    sim_kwargs, run_kwargs = CONFIGS[name]
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, f"{name}.sam")
        with open(path, "w") as fh:
            fh.write(simulate(SimSpec(**sim_kwargs)))
        cfg = RunConfig(prefix="mw", backend="jax", shards=1,
                        **run_kwargs)
        backend = JaxBackend()
        ai = open_alignment_input(path, "auto", binary=True)
        t0 = time.perf_counter()
        res = backend.run(ai.contigs, ai.stream, cfg)
        elapsed = time.perf_counter() - t0
        ai.close()
    extra = res.stats.extra
    from sam2consensus_tpu import observability

    man = observability.last_manifest() or {}
    cap = next((d for d in man.get("decisions", [])
                if d.get("decision") == "capacity"), {})
    fams = {k[len("mem/peak_bytes/"):]: round(v / 1e6, 3)
            for k, v in extra.items()
            if k.startswith("mem/peak_bytes/")}
    row = {
        "config": name,
        "reads": int(res.stats.reads_mapped),
        "total_len": cap.get("inputs", {}).get("total_len"),
        "jax_sec": round(elapsed, 3),
        "peak_rss_mb": extra.get("peak_rss_mb"),
        "peak_tracked_mb": round(
            extra.get("mem/peak_tracked_bytes", 0) / 1e6, 3),
        "family_peak_mb": fams,
        "device_peak_mb": round(
            extra.get("mem/device_peak_bytes", 0) / 1e6, 3)
        if extra.get("mem/device_peak_bytes") else None,
        "capacity_predicted_mb": round(
            cap.get("predicted", {}).get("bytes", 0) / 1e6, 3),
        "capacity_residual": cap.get("residual", {}).get("bytes"),
        "capacity_drift": cap.get("drift", False),
    }
    return row


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", default="-",
                   help="JSONL destination ('-' = stdout)")
    p.add_argument("--configs", default=",".join(CONFIGS),
                   help="comma-separated subset of: "
                        + ", ".join(CONFIGS))
    p.add_argument("--one", default=None, help=argparse.SUPPRESS)
    args = p.parse_args(argv)

    if args.one is not None:
        # subprocess mode: one config, one row on stdout
        print(json.dumps(run_one(args.one)))
        return 0

    names = [n for n in args.configs.split(",") if n]
    unknown = [n for n in names if n not in CONFIGS]
    if unknown:
        print(f"unknown config(s): {unknown}", file=sys.stderr)
        return 2
    rows = []
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    for name in names:
        print(f"[mem_watermark] {name}...", file=sys.stderr)
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--one", name],
            capture_output=True, text=True, env=env, timeout=900)
        if r.returncode != 0:
            err = (r.stderr.strip().splitlines() or ["no output"])[-1]
            print(f"[mem_watermark] {name} FAILED: {err}",
                  file=sys.stderr)
            rows.append({"config": name, "error": err})
            continue
        line = (r.stdout.strip().splitlines() or ["{}"])[-1]
        row = json.loads(line)
        rows.append(row)
        print(f"[mem_watermark] {name}: peak_rss {row['peak_rss_mb']} "
              f"MB, tracked {row['peak_tracked_mb']} MB, predicted "
              f"{row['capacity_predicted_mb']} MB (residual "
              f"{row['capacity_residual']})", file=sys.stderr)
    text = "".join(json.dumps(r) + "\n" for r in rows)
    if args.out == "-":
        sys.stdout.write(text)
    else:
        with open(args.out, "w") as fh:
            fh.write(text)
    bad = [r for r in rows if "error" in r]
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
