#!/usr/bin/env python3
"""Render a bench JSON line (bench.py stdout / BENCH_r*.json payload)
as a markdown table for PERF.md — one row per config with phases and
utilization inline.  Usage: python tools/bench_report.py <file.json>
(accepts either the raw one-line JSON or the driver's wrapper with a
"tail" field)."""

import json
import sys


def load(path):
    text = open(path).read().strip()
    try:
        obj = json.loads(text)
    except json.JSONDecodeError:
        obj = json.loads(text.splitlines()[-1])
    if "configs" not in obj and "tail" in obj:      # driver wrapper
        obj = json.loads(obj["tail"].strip().splitlines()[-1])
    return obj


def main():
    obj = load(sys.argv[1])
    print(f"device: {obj.get('device')}  headline: "
          f"{obj.get('value'):,} bases/s  vs_baseline: "
          f"{obj.get('vs_baseline')}x\n")
    print("| config | reads | jax s | cpu s | vs cpu | identical "
          "| phases | util |")
    print("|---|---|---|---|---|---|---|---|")
    for r in obj.get("configs", []):
        if "error" in r:
            print(f"| {r['config']} | — | — | — | — | ERROR | "
                  f"{r['error'][:60]} | |")
            continue
        ph = " ".join(f"{k.replace('_sec', '')}={v}"
                      for k, v in r.get("phases", {}).items())
        ut = " ".join(f"{k}={v}" for k, v in r.get("util", {}).items())
        est = "~" if r.get("cpu_sec_estimated") else ""
        print(f"| {r['config']} | {r.get('reads'):,} | {r.get('jax_sec')} "
              f"| {est}{r.get('cpu_sec')} | {est}{r.get('vs_baseline')}x "
              f"| {r.get('identical', 'n/a')} | {ph} | {ut} |")


if __name__ == "__main__":
    main()
