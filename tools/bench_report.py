#!/usr/bin/env python3
"""Render bench/observability artifacts as markdown tables for PERF.md.

Two input shapes, auto-detected:

* a bench JSON line (bench.py stdout / BENCH_r*.json payload, or the
  driver's wrapper with a "tail" field) — one row per config with
  phases and utilization inline;
* a metrics JSONL sink (the CLI's ``--metrics-out`` /
  ``observability.write_metrics_jsonl``) — a per-phase breakdown table
  plus counters/gauges/histograms, sourced from the registry itself
  instead of hand-parsing ``stats.extra`` keys.

Usage:
  python tools/bench_report.py <file.json|metrics.jsonl>
  python tools/bench_report.py --diff OLD NEW [--rel-floor F]

``--diff`` renders a per-phase/per-config delta table between two
artifacts (either shape, including head-truncated BENCH captures),
with each delta judged against the same noise floor the regression
gate uses (sam2consensus_tpu/observability/regress.py): deltas inside
the band print ``≈`` (rig noise, not a finding), outside it
``slower``/``faster``.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def load(path):
    text = open(path).read().strip()
    first = text.splitlines()[0] if text else ""
    try:
        head = json.loads(first)
    except json.JSONDecodeError:
        head = None
    if isinstance(head, dict) and head.get("kind") == "meta":
        return "metrics", [json.loads(ln) for ln in text.splitlines()
                           if ln.strip()]
    try:
        obj = json.loads(text)
    except json.JSONDecodeError:
        obj = json.loads(text.splitlines()[-1])
    if "configs" not in obj and isinstance(obj.get("parsed"), dict):
        obj = obj["parsed"]                         # driver wrapper
    elif "configs" not in obj and "tail" in obj:
        try:
            obj = json.loads(obj["tail"].strip().splitlines()[-1])
        except json.JSONDecodeError:
            sys.exit(f"{path}: driver wrapper's 'tail' capture is "
                     "truncated and 'parsed' is empty — re-run bench.py "
                     "for a complete JSON line")
    return "bench", obj


#: phases that are SUB-WINDOWS of the accumulate wall-clock window
#: (backends/jax_backend._run times accumulate around the whole
#: streaming loop, which contains decode/stage/pileup dispatch —
#: summing them with it would double-count)
SUB_OF_ACCUMULATE = ("decode", "stage", "pileup_dispatch")


def _fmt_val(v):
    return f"{v:,.0f}" if float(v).is_integer() else f"{v:.4f}"


def report_metrics(rows):
    """Per-phase breakdown + the rest of the registry, from the JSONL
    sink — the same numbers the stats.extra compat view exposes, read
    from the canonical source."""
    meta = next((r for r in rows if r.get("kind") == "meta"), {})
    print(f"metrics sink: backend={meta.get('backend', '?')} "
          f"pid={meta.get('pid', '?')}\n")
    phases = dict((r["name"][len("phase/"):-len("_sec")], r["value"])
                  for r in rows if r.get("kind") == "counter"
                  and r["name"].startswith("phase/")
                  and r["name"].endswith("_sec"))
    if phases:
        top = [(k, v) for k, v in phases.items()
               if k not in SUB_OF_ACCUMULATE]
        total = sum(v for _k, v in top)
        acc = phases.get("accumulate", 0.0)
        print("| phase | sec | % |")
        print("|---|---|---|")
        for name, v in top:
            pct = 100.0 * v / total if total > 0 else 0.0
            print(f"| {name} | {v:.4f} | {pct:.1f}% |")
            if name == "accumulate":
                # overlapped sub-windows, shown against their window
                for sub in SUB_OF_ACCUMULATE:
                    if sub in phases:
                        sv = phases[sub]
                        spct = 100.0 * sv / acc if acc > 0 else 0.0
                        print(f"| &nbsp;&nbsp;↳ {sub} | {sv:.4f} "
                              f"| {spct:.1f}% of accumulate |")
        print(f"| **total (non-overlapping)** | **{total:.4f}** | |\n")
    other = [r for r in rows if r.get("kind") == "counter"
             and not r["name"].startswith("phase/")]
    if other:
        print("| counter | value |")
        print("|---|---|")
        for r in other:
            print(f"| {r['name']} | {_fmt_val(r['value'])} |")
        print()
    gauges = [r for r in rows if r.get("kind") == "gauge"]
    for r in gauges:
        if "info" in r:
            info = " ".join(f"{k}={v}" for k, v in r["info"].items())
            print(f"- {r['name']}: {info}")
        else:
            print(f"- {r['name']}: {r['value']}")
    hists = [r for r in rows if r.get("kind") == "histogram"]
    if hists:
        print("\n| histogram | count | sum | p50 | p95 | p99 |")
        print("|---|---|---|---|---|---|")
        for r in hists:
            print(f"| {r['name']} | {r['count']} | {r['sum']:.4f} "
                  f"| {r['p50']:.4g} | {r['p95']:.4g} "
                  f"| {r['p99']:.4g} |")


def report_bench(obj):
    print(f"device: {obj.get('device')}  headline: "
          f"{obj.get('value'):,} bases/s  vs_baseline: "
          f"{obj.get('vs_baseline')}x\n")
    print("| config | reads | jax s | cpu s | vs cpu | identical "
          "| phases | util |")
    print("|---|---|---|---|---|---|---|---|")
    for r in obj.get("configs", []):
        if "error" in r:
            print(f"| {r['config']} | — | — | — | — | ERROR | "
                  f"{r['error'][:60]} | |")
            continue
        ph = " ".join(f"{k.replace('_sec', '')}={v}"
                      for k, v in r.get("phases", {}).items())
        ut = " ".join(f"{k}={v}" for k, v in r.get("util", {}).items()
                      if not isinstance(v, dict))
        est = "~" if r.get("cpu_sec_estimated") else ""
        print(f"| {r['config']} | {r.get('reads'):,} | {r.get('jax_sec')} "
              f"| {est}{r.get('cpu_sec')} | {est}{r.get('vs_baseline')}x "
              f"| {r.get('identical', 'n/a')} | {ph} | {ut} |")


def _series_for_diff(path):
    """``{series_label: seconds}`` from either artifact shape, for the
    --diff table.  Bench artifacts (incl. truncated driver captures)
    contribute ``<config>.jax_sec`` plus ``<config>.<phase>``; metrics
    JSONL sinks contribute the phase counters."""
    from sam2consensus_tpu.observability import regress

    text = open(path).read().strip()
    first = text.splitlines()[0] if text else ""
    try:
        head = json.loads(first)
    except json.JSONDecodeError:
        head = None
    if isinstance(head, dict) and head.get("kind") == "meta":
        rows = [json.loads(ln) for ln in text.splitlines() if ln.strip()]
        return {r["name"]: r["value"] for r in rows
                if r.get("kind") == "counter"
                and r["name"].startswith("phase/")}
    out = {}
    for row in regress.load_bench_artifact(path):
        if "error" in row or "config" not in row:
            continue
        cfg = row["config"]
        if isinstance(row.get("jax_sec"), (int, float)):
            out[f"{cfg}.jax_sec"] = float(row["jax_sec"])
        for ph, v in (row.get("phases") or {}).items():
            if isinstance(v, (int, float)):
                out[f"{cfg}.{ph}"] = float(v)
    return out


def report_diff(old_path, new_path, rel_floor=None):
    """Per-phase delta table OLD -> NEW, noise-judged by the regression
    gate's band logic (two points have no MAD, so the band is the
    relative noise floor alone)."""
    from sam2consensus_tpu.observability import regress

    if rel_floor is None:
        rel_floor = regress.DEFAULT_REL_FLOOR
    old = _series_for_diff(old_path)
    new = _series_for_diff(new_path)
    keys = sorted(set(old) & set(new))
    if not keys:
        print("no comparable series between the two artifacts",
              file=sys.stderr)
        return 2
    print(f"diff: {old_path} -> {new_path} "
          f"(noise floor ±{rel_floor * 100:.0f}%)\n")
    print("| series | old s | new s | Δ | verdict |")
    print("|---|---|---|---|---|")
    slower = 0
    for k in keys:
        o, n = old[k], new[k]
        allowed = regress.noise_floor(o, 0.0, rel_floor=rel_floor)
        delta = n - o
        pct = f"{100.0 * delta / o:+.1f}%" if o else "—"
        if delta > allowed:
            verdict = "slower"
            slower += 1
        elif delta < -allowed:
            verdict = "faster"
        else:
            verdict = "≈"
        print(f"| {k} | {o:.4f} | {n:.4f} | {pct} | {verdict} |")
    print(f"\n{len(keys)} series, {slower} slower beyond the noise floor")
    return 0


def main(argv=None):
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "--diff":
        rest = argv[1:]
        rel_floor = None
        if "--rel-floor" in rest:
            i = rest.index("--rel-floor")
            rel_floor = float(rest[i + 1])
            del rest[i:i + 2]
        if len(rest) != 2:
            sys.exit("usage: bench_report.py --diff OLD NEW "
                     "[--rel-floor F]")
        return report_diff(rest[0], rest[1], rel_floor)
    kind, payload = load(argv[0])
    if kind == "metrics":
        report_metrics(payload)
    else:
        report_bench(payload)
    return 0


if __name__ == "__main__":
    sys.exit(main())
