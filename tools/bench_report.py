#!/usr/bin/env python3
"""Render bench/observability artifacts as markdown tables for PERF.md.

Two input shapes, auto-detected:

* a bench JSON line (bench.py stdout / BENCH_r*.json payload, or the
  driver's wrapper with a "tail" field) — one row per config with
  phases and utilization inline;
* a metrics JSONL sink (the CLI's ``--metrics-out`` /
  ``observability.write_metrics_jsonl``) — a per-phase breakdown table
  plus counters/gauges/histograms, sourced from the registry itself
  instead of hand-parsing ``stats.extra`` keys.

Usage: python tools/bench_report.py <file.json|metrics.jsonl>
"""

import json
import sys


def load(path):
    text = open(path).read().strip()
    first = text.splitlines()[0] if text else ""
    try:
        head = json.loads(first)
    except json.JSONDecodeError:
        head = None
    if isinstance(head, dict) and head.get("kind") == "meta":
        return "metrics", [json.loads(ln) for ln in text.splitlines()
                           if ln.strip()]
    try:
        obj = json.loads(text)
    except json.JSONDecodeError:
        obj = json.loads(text.splitlines()[-1])
    if "configs" not in obj and isinstance(obj.get("parsed"), dict):
        obj = obj["parsed"]                         # driver wrapper
    elif "configs" not in obj and "tail" in obj:
        try:
            obj = json.loads(obj["tail"].strip().splitlines()[-1])
        except json.JSONDecodeError:
            sys.exit(f"{path}: driver wrapper's 'tail' capture is "
                     "truncated and 'parsed' is empty — re-run bench.py "
                     "for a complete JSON line")
    return "bench", obj


#: phases that are SUB-WINDOWS of the accumulate wall-clock window
#: (backends/jax_backend._run times accumulate around the whole
#: streaming loop, which contains decode/stage/pileup dispatch —
#: summing them with it would double-count)
SUB_OF_ACCUMULATE = ("decode", "stage", "pileup_dispatch")


def _fmt_val(v):
    return f"{v:,.0f}" if float(v).is_integer() else f"{v:.4f}"


def report_metrics(rows):
    """Per-phase breakdown + the rest of the registry, from the JSONL
    sink — the same numbers the stats.extra compat view exposes, read
    from the canonical source."""
    meta = next((r for r in rows if r.get("kind") == "meta"), {})
    print(f"metrics sink: backend={meta.get('backend', '?')} "
          f"pid={meta.get('pid', '?')}\n")
    phases = dict((r["name"][len("phase/"):-len("_sec")], r["value"])
                  for r in rows if r.get("kind") == "counter"
                  and r["name"].startswith("phase/")
                  and r["name"].endswith("_sec"))
    if phases:
        top = [(k, v) for k, v in phases.items()
               if k not in SUB_OF_ACCUMULATE]
        total = sum(v for _k, v in top)
        acc = phases.get("accumulate", 0.0)
        print("| phase | sec | % |")
        print("|---|---|---|")
        for name, v in top:
            pct = 100.0 * v / total if total > 0 else 0.0
            print(f"| {name} | {v:.4f} | {pct:.1f}% |")
            if name == "accumulate":
                # overlapped sub-windows, shown against their window
                for sub in SUB_OF_ACCUMULATE:
                    if sub in phases:
                        sv = phases[sub]
                        spct = 100.0 * sv / acc if acc > 0 else 0.0
                        print(f"| &nbsp;&nbsp;↳ {sub} | {sv:.4f} "
                              f"| {spct:.1f}% of accumulate |")
        print(f"| **total (non-overlapping)** | **{total:.4f}** | |\n")
    other = [r for r in rows if r.get("kind") == "counter"
             and not r["name"].startswith("phase/")]
    if other:
        print("| counter | value |")
        print("|---|---|")
        for r in other:
            print(f"| {r['name']} | {_fmt_val(r['value'])} |")
        print()
    gauges = [r for r in rows if r.get("kind") == "gauge"]
    for r in gauges:
        if "info" in r:
            info = " ".join(f"{k}={v}" for k, v in r["info"].items())
            print(f"- {r['name']}: {info}")
        else:
            print(f"- {r['name']}: {r['value']}")
    hists = [r for r in rows if r.get("kind") == "histogram"]
    if hists:
        print("\n| histogram | count | sum | p50 | p95 | p99 |")
        print("|---|---|---|---|---|---|")
        for r in hists:
            print(f"| {r['name']} | {r['count']} | {r['sum']:.4f} "
                  f"| {r['p50']:.4g} | {r['p95']:.4g} "
                  f"| {r['p99']:.4g} |")


def report_bench(obj):
    print(f"device: {obj.get('device')}  headline: "
          f"{obj.get('value'):,} bases/s  vs_baseline: "
          f"{obj.get('vs_baseline')}x\n")
    print("| config | reads | jax s | cpu s | vs cpu | identical "
          "| phases | util |")
    print("|---|---|---|---|---|---|---|---|")
    for r in obj.get("configs", []):
        if "error" in r:
            print(f"| {r['config']} | — | — | — | — | ERROR | "
                  f"{r['error'][:60]} | |")
            continue
        ph = " ".join(f"{k.replace('_sec', '')}={v}"
                      for k, v in r.get("phases", {}).items())
        ut = " ".join(f"{k}={v}" for k, v in r.get("util", {}).items()
                      if not isinstance(v, dict))
        est = "~" if r.get("cpu_sec_estimated") else ""
        print(f"| {r['config']} | {r.get('reads'):,} | {r.get('jax_sec')} "
              f"| {est}{r.get('cpu_sec')} | {est}{r.get('vs_baseline')}x "
              f"| {r.get('identical', 'n/a')} | {ph} | {ut} |")


def main():
    kind, payload = load(sys.argv[1])
    if kind == "metrics":
        report_metrics(payload)
    else:
        report_bench(payload)


if __name__ == "__main__":
    main()
