#!/usr/bin/env python3
"""Shard-mode sweep: steady-state dp/sp/dpsp throughput vs auto's pick.

The evidence harness for the model-driven ``--shard-mode auto``
(parallel/auto.py; round-4 verdict #3).  Each cell fixes a workload
shape — (genome length x slab depth x position pattern) — builds
identical segment-row slabs, and measures every feasible layout's
STEADY-STATE per-slab accumulate time (one warm pass pays the jit
compiles, then timed repeats), asserting cell-exact equality against
the unsharded scatter oracle.  ``auto`` is the model's pick for the
cell's first slab; the summary reports how often that pick lands
within 10% of the measured best (the verdict's done criterion).

Why accumulator-level and not whole-backend: a full CLI run on the
8-virtual-device CPU mesh is dominated by per-run jit compilation of
the shard_map graphs (seconds, paid once per process in production)
and one-core oracle noise — it measures the harness, not the layouts.
The per-slab accumulate is exactly the quantity the model prices.

Run:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/shard_sweep.py > campaign/shard_sweep_r05.jsonl
"""

import json
import os
import sys
import time
import zlib

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

from sam2consensus_tpu.utils.platform import pin_platform_from_env  # noqa
pin_platform_from_env()

import numpy as np  # noqa: E402
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from sam2consensus_tpu.encoder.events import SegmentBatch  # noqa: E402
from sam2consensus_tpu.ops.pileup import PileupAccumulator  # noqa: E402
from sam2consensus_tpu.parallel import auto as shard_auto  # noqa: E402
from sam2consensus_tpu.parallel.dp import ShardedConsensus  # noqa: E402
from sam2consensus_tpu.parallel.dpsp import ProductShardedConsensus  # noqa: E402
from sam2consensus_tpu.parallel.mesh import make_mesh  # noqa: E402
from sam2consensus_tpu.parallel.sp import PositionShardedConsensus  # noqa: E402


def emit(**kw):
    print(json.dumps(kw), flush=True)


def make_slabs(L, rows, w, pattern, n_slabs, seed):
    """Identical-shape slabs; ``pattern``: uniform | sorted | clustered.

    ``sorted`` mimics a coordinate-sorted stream: each slab covers the
    next contiguous position window.  ``clustered`` concentrates ~90%
    of rows in one 1/16th of the genome without a narrow window
    (window-ineligible but imbalanced — the dpsp case).
    """
    rng = np.random.default_rng(seed)
    slabs = []
    for i in range(n_slabs):
        if pattern == "sorted":
            lo = L * i // n_slabs
            hi = max(lo + w + 1, L * (i + 1) // n_slabs)
            starts = np.sort(rng.integers(lo, max(lo + 1, hi - w), rows))
        elif pattern == "clustered":
            k = int(rows * 0.9)
            c0 = (L // 16) * (i % 8)
            a = rng.integers(c0, max(c0 + 1, c0 + L // 16 - w), k)
            b = rng.integers(0, max(1, L - w), rows - k)
            starts = np.concatenate([a, b])
        else:
            starts = rng.integers(0, max(1, L - w), rows)
        codes = rng.integers(0, 6, (rows, w)).astype(np.uint8)
        codes[rng.random(codes.shape) < 0.05] = 255
        slabs.append((starts.astype(np.int32), codes))
    return slabs


def batch_of(starts, codes):
    return SegmentBatch(buckets={codes.shape[1]: (starts, codes)},
                        n_reads=len(starts),
                        n_events=int((codes < 6).sum()))


def build_acc(mode, mesh, L, halo):
    if mode == "sp":
        return PositionShardedConsensus(mesh, L, halo=halo)
    if mode == "dpsp":
        return ProductShardedConsensus(mesh, L, halo=halo)
    return ShardedConsensus(mesh, L, pileup="scatter")


def main():
    reps = int(os.environ.get("SWEEP_REPS", "3"))
    n_slabs = int(os.environ.get("SWEEP_SLABS", "2"))
    w = 128
    cells = [
        # (name, L, rows_per_slab, pattern)
        ("small_uniform", 100_000, 32_768, "uniform"),
        ("small_sorted", 100_000, 32_768, "sorted"),
        ("mid_uniform", 4_000_000, 32_768, "uniform"),
        ("mid_sorted", 4_000_000, 32_768, "sorted"),
        ("mid_clustered", 4_000_000, 32_768, "clustered"),
        ("large_uniform", 32_000_000, 32_768, "uniform"),
        ("large_sorted", 32_000_000, 32_768, "sorted"),
        ("large_clustered", 32_000_000, 32_768, "clustered"),
        ("large_shallow", 32_000_000, 4_096, "uniform"),
    ]
    from sam2consensus_tpu.backends.jax_backend import _link_constants
    # on the virtual CPU mesh "device_put" is a memcpy, not a tunnel;
    # the model must price the rig it actually runs on (override with
    # S2C_TAIL_LINK_MBPS to sweep the tunnel-rig decision surface)
    os.environ.setdefault("S2C_TAIL_LINK_MBPS", "5000")
    _rt, link_bps = _link_constants()
    n = 8
    within = 0
    total = 0
    for name, L, rows, pattern in cells:
        # crc32, not builtin hash(): str hashing is salted per process
        # (PYTHONHASHSEED), which made committed sweep artifacts
        # irreproducible run-to-run (ADVICE r5 #1)
        slabs = make_slabs(L, rows, w, pattern, n_slabs,
                           seed=zlib.crc32(name.encode()) % 2**31)
        # oracle counts (unsharded scatter)
        oracle = PileupAccumulator(L, strategy="scatter")
        for s, c in slabs:
            oracle.add(batch_of(s, c))
        want = oracle.counts_host()

        stats = shard_auto.slab_stats(batch_of(*slabs[0]).buckets, L)
        rows_obs, rb, max_w, peak, sfrac = stats
        halo = min(1 << 16, max(64, max_w))
        mesh = make_mesh(n)
        pick = shard_auto.choose_shard_mode(
            L, n, dict(mesh.shape), rows_obs, rb, peak, sfrac, halo,
            link_bps)
        row = {"cell": name, "L": L, "rows": rows, "pattern": pattern,
               "auto_pick": pick,
               "slab": {"peak_frac": round(peak, 3),
                        "sorted_frac": round(sfrac, 3), "halo": halo}}
        times = {}
        for mode in ("dp", "sp", "dpsp"):
            try:
                acc = build_acc(mode, make_mesh(n), L, halo)
                for s, c in slabs:            # warm: pays jit compiles
                    acc.add(batch_of(s, c))
                acc.sync()
                t0 = time.perf_counter()
                for _ in range(reps):
                    for s, c in slabs:
                        acc.add(batch_of(s, c))
                acc.sync()
                dt = (time.perf_counter() - t0) / (reps * n_slabs)
                got = acc.counts_host()
                ok = np.array_equal(got, want * (reps + 1))
                times[mode] = dt
                row[mode] = {"sec_per_slab": round(dt, 4),
                             "identical": bool(ok)}
                if not ok:
                    row[mode]["identical"] = False
            except (ValueError, MemoryError) as exc:
                row[mode] = f"infeasible: {exc}"[:90]
        if times and pick in times:
            best = min(times, key=times.get)
            ratio = times[pick] / times[best]
            row["best"] = best
            row["auto_vs_best"] = round(ratio, 3)
            total += 1
            if ratio <= 1.10:
                within += 1
        emit(**row)
    emit(summary=True, cells=total, auto_within_10pct=within,
         criterion_met=bool(within == total))


if __name__ == "__main__":
    main()
