#!/usr/bin/env python3
"""Cohort-scale serving benchmark (the ISSUE-20 tentpole's evidence).

Simulates N shared-reference samples, lists them in ONE manifest, and
streams them through serve/cohort.py in packed waves — then measures
the same job class through the plain packed-stranger path (PR 11's
batch scheduler with no cohort planning) and a fresh serial runner for
byte-identity spot checks.  One JSON row per wave/leg plus a summary
row as JSONL (``--out``; stdout otherwise).

The summary's acceptance fields: ``identical`` (spot-checked members
byte-equal to serial), ``concordance_pinned`` (mini-cohort concordance
digest == CPU oracle digest), ``replans_after_wave1`` /
``new_compiles_after_wave1`` (both 0: one PanelGeometry + one compile
footprint cover every wave), ``residual_in_band`` (no cohort_wave
decision drifted once learned), ``cohort_ge_stranger`` (cohort jobs/s
>= packed-stranger jobs/s), and the rolled-up ``ok``.

Campaign usage (tools/tpu_campaign.sh step ``cohort``) runs 10k small
samples; the CPU-fallback harness proof lives at
campaign/cohort_r06_cpufallback.jsonl.

Usage: python tools/cohort_bench.py [--samples 200] [--reads 64]
       [--contig-len 1500] [--wave 0] [--spot-checks 20]
       [--mem-budget BYTES] [--out FILE.jsonl]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--samples", type=int, default=200)
    ap.add_argument("--reads", type=int, default=64)
    ap.add_argument("--contig-len", type=int, default=1500)
    ap.add_argument("--read-len", type=int, default=100)
    ap.add_argument("--wave", type=int, default=0,
                    help="fixed wave size (0 = rate-sized, the serve "
                         "default)")
    ap.add_argument("--stranger-n", type=int, default=0,
                    help="members for the packed-stranger comparison "
                         "leg (0 = 4x the stranger batch)")
    ap.add_argument("--stranger-batch", type=int, default=8)
    ap.add_argument("--spot-checks", type=int, default=20)
    ap.add_argument("--pin-members", type=int, default=24,
                    help="mini-cohort size for the concordance-vs-"
                         "oracle pin")
    ap.add_argument("--mem-budget", type=int, default=0,
                    help="bytes; forwarded to the runner so wave "
                         "sizing must respect it (0 = unbudgeted)")
    ap.add_argument("--out", default=None,
                    help="JSONL destination (default: stdout)")
    args = ap.parse_args(argv)

    from sam2consensus_tpu.utils.platform import pin_platform_from_env

    pin_platform_from_env()

    from sam2consensus_tpu.serve.benchmark import run_cohort_bench

    res = run_cohort_bench(
        n_samples=args.samples, n_reads=args.reads,
        contig_len=args.contig_len, read_len=args.read_len,
        wave=args.wave, stranger_n=args.stranger_n,
        stranger_batch=args.stranger_batch,
        spot_checks=args.spot_checks, pin_members=args.pin_members,
        mem_budget=args.mem_budget, log=log)
    lines = [json.dumps(r) for r in res["rows"]]
    lines.append(json.dumps(res["summary"]))
    blob = "\n".join(lines) + "\n"
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(blob)
        log(f"[cohort_bench] wrote {args.out}")
    else:
        sys.stdout.write(blob)
    return 0 if res["summary"]["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
