#!/usr/bin/env python3
"""Measure decode and native-vote throughput at 1/2/4 threads.

The multi-threaded paths (--decode-threads: ``encoder/parallel_decode.py``
fused decode workers; the threaded ``s2c_vote`` position ranges) carry the
framework's multi-core story, but the round-3 verdict noted every claim
about them was unmeasured (the bench host has one core).  This tool
records what the current host CAN measure — per-thread-count rates plus
the host's core count, so the artifact is honest about whether the run
could exhibit scaling at all — as one JSON line per measurement.

Usage: python tools/thread_scaling.py [> artifact.jsonl]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def emit(row):
    row["host_cores"] = os.cpu_count()
    print(json.dumps(row), flush=True)


def measure_decode(threads_list, n_reads=500_000):
    from sam2consensus_tpu.encoder.events import GenomeLayout
    from sam2consensus_tpu.encoder.parallel_decode import ParallelFusedDecoder
    from sam2consensus_tpu.io.sam import ReadStream, opener, read_header
    from sam2consensus_tpu.utils.simulate import SimSpec, simulate
    import io
    import tempfile

    spec = SimSpec(n_contigs=200, contig_len=2000, n_reads=n_reads,
                   read_len=100, ins_read_rate=0.05, del_read_rate=0.05,
                   seed=99)
    log(f"[decode] simulating {n_reads} reads ...")
    text = simulate(spec)
    with tempfile.NamedTemporaryFile("w", suffix=".sam",
                                     delete=False) as fh:
        fh.write(text)
        path = fh.name
    try:
        handle = opener(path, binary=True)
        contigs, _n, first = read_header(handle)
        layout = GenomeLayout(contigs)
        blocks = list(ReadStream(handle, first).blocks())
        handle.close()
        total_mb = sum(len(b) for b in blocks) / 1e6
        for nt in threads_list:
            best = None
            for _rep in range(3):
                counts = np.zeros((layout.total_len, 6), dtype=np.int32)
                dec = ParallelFusedDecoder(layout, counts, n_threads=nt)
                t0 = time.perf_counter()
                for _ in dec.encode_blocks(iter(blocks)):
                    pass
                dt = time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            emit({"metric": "fused_decode", "threads": nt,
                  "effective_threads": dec.n_threads,
                  "sec": round(best, 4),
                  "mb_per_s": round(total_mb / best, 1),
                  "reads": dec.n_reads})
            log(f"[decode] threads={nt}: {best:.3f}s "
                f"({total_mb / best:.0f} MB/s)")
    finally:
        os.unlink(path)


def measure_vote(threads_list, L=4 << 20):
    from sam2consensus_tpu import native
    from sam2consensus_tpu.ops.vote import vote_positions_native

    if native.load() is None:
        emit({"metric": "native_vote", "error": "native lib unavailable"})
        return
    rng = np.random.default_rng(7)
    counts = rng.integers(0, 60, (L, 6)).astype(np.int32)
    for T, thresholds in ((1, [0.25]), (3, [0.25, 0.5, 0.75])):
        for nt in threads_list:
            best = None
            for _rep in range(3):
                t0 = time.perf_counter()
                out = vote_positions_native(counts, thresholds, 1,
                                            threads=nt)
                dt = time.perf_counter() - t0
                assert out is not None
                best = dt if best is None else min(best, dt)
            emit({"metric": "native_vote", "threads": nt,
                  "n_thresholds": T, "positions": L,
                  "sec": round(best, 4),
                  "mpos_per_s_per_thr": round(L / best / 1e6 / T, 1)})
            log(f"[vote] T={T} threads={nt}: {best:.3f}s "
                f"({L / best / 1e6:.0f} Mpos/s)")


def main():
    threads_list = [int(t) for t in os.environ.get(
        "S2C_SCALING_THREADS", "1,2,4").split(",")]
    measure_decode(threads_list)
    measure_vote(threads_list)
    return 0


if __name__ == "__main__":
    sys.exit(main())
