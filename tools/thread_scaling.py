#!/usr/bin/env python3
"""Measure ingest and native-vote throughput at 1/2/4 threads.

The multi-threaded paths (--decode-threads: the byte-shard scheduler in
``encoder/parallel_decode.py``; the threaded ``s2c_vote`` position
ranges; the BGZF/BAM block-parallel ingest) carry the framework's
multi-core story.  This tool records what the current host CAN measure
— per-thread-count rates plus the host's core count, so the artifact is
honest about whether the run could exhibit scaling at all — as one JSON
line per measurement.

Legs (all best-of-``S2C_SCALING_REPS``, default 5 — the scaling hosts
are noisy VMs and the bench convention is best-of-N):

* ``serial_decode`` — the plain fused NativeReadEncoder over a file
  (the 1-thread floor every speedup row is judged against);
* ``fused_decode`` — the shard rung (``encode_input`` over a real
  file: mmap + line-snapped byte ranges, one worker per shard);
* ``fused_decode_stream`` — the queue-feed streaming rung (what gzip
  inputs get), so the fallback's cost is a number, not a guess;
* ``bam_ingest`` — the binary BAM leg: BGZF stripes on the shared
  ingest pool + the native record decoder;
* ``native_vote`` — the threaded C++ position vote.

Usage: python tools/thread_scaling.py [> artifact.jsonl]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def _read_int(path):
    try:
        with open(path) as fh:
            return int(fh.read().split()[0])
    except (OSError, ValueError, IndexError):
        return None


def _cpu_limits():
    """cgroup CPU constraints, schemes kept distinct: a host with 2
    cores but a 1.5-CPU budget can only show full 2-thread scaling in
    burst windows — the artifact says so instead of letting the reader
    assume 2 unthrottled cores.

    ``cpu_shares`` (v1) and ``cpu_weight`` (v2) are RELATIVE weights on
    different bases (1024 vs 100) — never merged into one field.
    ``cpu_quota`` is the actual hard cap in CPUs (v1
    cfs_quota_us/cfs_period_us, v2 cpu.max), emitted only when set."""
    out = {}
    shares = _read_int("/sys/fs/cgroup/cpu/cpu.shares")
    if shares is not None:                       # cgroup v1
        out["cpu_shares"] = shares
        quota = _read_int("/sys/fs/cgroup/cpu/cpu.cfs_quota_us")
        period = _read_int("/sys/fs/cgroup/cpu/cpu.cfs_period_us")
        if quota and period and quota > 0:
            out["cpu_quota"] = round(quota / period, 3)
        return out
    weight = _read_int("/sys/fs/cgroup/cpu.weight")
    if weight is not None:                       # cgroup v2
        out["cpu_weight"] = weight
        try:
            with open("/sys/fs/cgroup/cpu.max") as fh:
                q, p = fh.read().split()
                if q != "max":
                    out["cpu_quota"] = round(int(q) / int(p), 3)
        except (OSError, ValueError):
            pass
    return out


def emit(row):
    row["host_cores"] = os.cpu_count()
    row.update(_cpu_limits())
    print(json.dumps(row), flush=True)


def _reps():
    return max(1, int(os.environ.get("S2C_SCALING_REPS", "5")))


def _best(fn):
    best = None
    for _ in range(_reps()):
        dt = fn()
        best = dt if best is None else min(best, dt)
    return best


def _sim_sam(n_reads, tmpdir):
    from sam2consensus_tpu.utils.simulate import SimSpec, simulate

    spec = SimSpec(n_contigs=200, contig_len=2000, n_reads=n_reads,
                   read_len=100, ins_read_rate=0.05, del_read_rate=0.05,
                   seed=99)
    log(f"[sim] {n_reads} reads ...")
    text = simulate(spec)
    path = os.path.join(tmpdir, "scaling.sam")
    with open(path, "w") as fh:
        fh.write(text)
    return path, os.path.getsize(path)


def measure_decode(threads_list, n_reads=500_000):
    import tempfile

    from sam2consensus_tpu.encoder.events import GenomeLayout
    from sam2consensus_tpu.encoder.native_encoder import NativeReadEncoder
    from sam2consensus_tpu.encoder.parallel_decode import \
        ParallelFusedDecoder
    from sam2consensus_tpu.io.sam import ReadStream, opener, read_header

    with tempfile.TemporaryDirectory() as tmp:
        path, total_b = _sim_sam(n_reads, tmp)
        total_mb = total_b / 1e6

        def open_stream():
            handle = opener(path, binary=True)
            contigs, _n, first = read_header(handle)
            return handle, GenomeLayout(contigs), \
                ReadStream(handle, first)

        def serial_once():
            handle, layout, stream = open_stream()
            counts = np.zeros((layout.total_len, 6), dtype=np.int32)
            enc = NativeReadEncoder(layout, accumulate_into=counts)
            t0 = time.perf_counter()
            for _ in enc.encode_blocks(stream.blocks()):
                pass
            dt = time.perf_counter() - t0
            handle.close()
            return dt

        best = _best(serial_once)
        emit({"metric": "serial_decode", "threads": 1,
              "sec": round(best, 4),
              "mb_per_s": round(total_mb / best, 1)})
        log(f"[decode] serial: {best:.3f}s ({total_mb / best:.0f} MB/s)")

        def rung_once(nt, rung):
            handle, layout, stream = open_stream()
            counts = np.zeros((layout.total_len, 6), dtype=np.int32)
            dec = ParallelFusedDecoder(layout, counts, n_threads=nt)
            t0 = time.perf_counter()
            src = dec.encode_input(stream) if rung == "shards" \
                else dec.encode_blocks(stream.blocks())
            for _ in src:
                pass
            dt = time.perf_counter() - t0
            handle.close()
            return dt, dec

        for rung, metric in (("shards", "fused_decode"),
                             ("stream", "fused_decode_stream")):
            for nt in threads_list:
                best, dec = None, None
                for _ in range(_reps()):
                    dt, d = rung_once(nt, rung)
                    if best is None or dt < best:
                        best, dec = dt, d
                emit({"metric": metric, "rung": rung, "threads": nt,
                      "effective_threads": dec.n_threads,
                      "sec": round(best, 4),
                      "mb_per_s": round(total_mb / best, 1),
                      "reads": dec.n_reads})
                log(f"[decode] {rung} threads={nt}: {best:.3f}s "
                    f"({total_mb / best:.0f} MB/s)")


def measure_bam(threads_list, n_reads=300_000):
    import tempfile

    from sam2consensus_tpu.config import RunConfig
    from sam2consensus_tpu.encoder.events import GenomeLayout
    from sam2consensus_tpu.formats import open_alignment_input
    from sam2consensus_tpu.formats.bam import sam_text_to_bam

    with tempfile.TemporaryDirectory() as tmp:
        path, _b = _sim_sam(n_reads, tmp)
        with open(path, "r") as fh:
            text = fh.read()
        bam = os.path.join(tmp, "scaling.bam")
        sam_text_to_bam(text, bam)
        total_mb = os.path.getsize(bam) / 1e6
        log(f"[bam] converted ({total_mb:.1f} MB compressed)")

        def once(nt):
            ai = open_alignment_input(bam, "bam", threads=nt)
            layout = GenomeLayout(ai.contigs)
            cfg = RunConfig(decode_threads=nt)
            enc, batches = ai.stream.make_encoder(layout, cfg, None)
            t0 = time.perf_counter()
            for _ in batches:
                pass
            dt = time.perf_counter() - t0
            ai.close()
            return dt, enc

        for nt in threads_list:
            best, enc = None, None
            for _ in range(_reps()):
                dt, e = once(nt)
                if best is None or dt < best:
                    best, enc = dt, e
            emit({"metric": "bam_ingest", "threads": nt,
                  "sec": round(best, 4),
                  "bam_mb_per_s": round(total_mb / best, 1),
                  "reads": enc.n_reads})
            log(f"[bam] threads={nt}: {best:.3f}s "
                f"({total_mb / best:.0f} compressed MB/s)")


def measure_vote(threads_list, L=4 << 20):
    from sam2consensus_tpu import native
    from sam2consensus_tpu.ops.vote import vote_positions_native

    if native.load() is None:
        emit({"metric": "native_vote", "error": "native lib unavailable"})
        return
    rng = np.random.default_rng(7)
    counts = rng.integers(0, 60, (L, 6)).astype(np.int32)
    for T, thresholds in ((1, [0.25]), (3, [0.25, 0.5, 0.75])):
        for nt in threads_list:
            def once():
                t0 = time.perf_counter()
                out = vote_positions_native(counts, thresholds, 1,
                                            threads=nt)
                assert out is not None
                return time.perf_counter() - t0

            best = _best(once)
            emit({"metric": "native_vote", "threads": nt,
                  "n_thresholds": T, "positions": L,
                  "sec": round(best, 4),
                  "mpos_per_s_per_thr": round(L / best / 1e6 / T, 1)})
            log(f"[vote] T={T} threads={nt}: {best:.3f}s "
                f"({L / best / 1e6:.0f} Mpos/s)")


def main():
    threads_list = [int(t) for t in os.environ.get(
        "S2C_SCALING_THREADS", "1,2,4").split(",")]
    measure_decode(threads_list)
    measure_bam(threads_list)
    measure_vote(threads_list)
    return 0


if __name__ == "__main__":
    sys.exit(main())
