#!/usr/bin/env python3
"""s2c_top: live serve-fleet status for operators WITHOUT a Prometheus
stack — a curses-free top(1) over the two files a telemetry-enabled
server already writes:

    python tools/s2c_top.py --health health.json --telemetry metrics.prom
    python tools/s2c_top.py --health health.json --once       # one frame

Fleet mode (``--fleet``): ``--health`` / ``--telemetry`` become GLOBS
over N workers' atomically-written files (each worker runs with its
own ``--health-out``/``--telemetry-out``; exposition samples carry
``worker`` labels), merged into one aggregated frame — fleet totals,
a per-worker liveness/lease table, the shared journal's position, and
the merged per-tenant SLO view:

    python tools/s2c_top.py --fleet --health 'ops/health-*.json' \\
        --telemetry 'ops/metrics-*.prom'

Polls the atomic health snapshot (``s2c serve --health-out``) and the
OpenMetrics exposition (``--telemetry-out``) every ``--interval``
seconds and renders: uptime, queue depth, the in-flight job + its age,
heartbeat age (a GROWING age with an in-flight job is the
wedged-dispatch signature), per-tenant ladder rung + SLO p50/p99
end-to-end latency + violation burn, bad-record/poison tallies, drift
events, and the last profiler capture.  Renders with plain ANSI
clear-screen — works over ssh, in tmux, and in a CI log (``--once``).

Both files are rewritten atomically by the server (one shared writer,
``observability/telemetry.atomic_write_text``), so a read never sees a
torn frame; a missing file renders as "waiting" rather than crashing —
the poller may simply have started before the server.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def read_health(path):
    try:
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def read_telemetry(path):
    """Exposition -> {(name, labelitems): value} sample map (None when
    absent/torn — the renderer degrades to health-only)."""
    from sam2consensus_tpu.observability.telemetry import \
        parse_openmetrics

    try:
        with open(path, encoding="utf-8") as fh:
            text = fh.read()
        return parse_openmetrics(text)
    except (OSError, ValueError):
        return None


def _sample(samples, name, **labels):
    for s in samples or ():
        if s["name"] != name:
            continue
        if all(s["labels"].get(k) == v for k, v in labels.items()):
            return s["value"]
    return None


def _tenants(samples):
    out = set()
    for s in samples or ():
        t = s["labels"].get("tenant")
        if t:
            out.add(t)
    return sorted(out)


def _age_fmt(sec):
    if sec is None:
        return "-"
    if sec < 120:
        return f"{sec:.1f}s"
    if sec < 7200:
        return f"{sec / 60:.1f}m"
    return f"{sec / 3600:.1f}h"


def render(health, samples, now=None):
    """One status frame as a list of lines (pure — pinned by tests)."""
    lines = []
    if health is None:
        return ["s2c_top: waiting for health snapshot..."]
    hb = health.get("last_heartbeat_age_sec")
    inflight = health.get("in_flight")
    lines.append(
        f"s2c serve  up {_age_fmt(health.get('uptime_sec'))}  "
        f"queue {health.get('queue_depth', 0)}  "
        f"jobs {health.get('jobs', {}).get('run', 0)} "
        f"({health.get('jobs', {}).get('failed', 0)} failed, "
        f"{health.get('jobs', {}).get('watchdog_timeouts', 0)} timeouts)")
    flag = ""
    if inflight and hb is not None and hb > 5.0:
        flag = "  << heartbeat aging: possible wedge"
    lines.append(
        f"in-flight: {inflight or '-'}"
        + (f" (age {_age_fmt(health.get('in_flight_sec'))})"
           if inflight else "")
        + f"  heartbeat age {_age_fmt(hb)}{flag}")
    adm = health.get("admission", {})
    lines.append(
        f"admission: {adm.get('admitted', 0)} admitted, "
        f"{adm.get('rejected', 0)} rejected, "
        f"{adm.get('pinned', 0)} pinned, "
        f"{adm.get('poison', 0)} poison; "
        f"bad records {health.get('bad_records', 0)}")
    # fleet mode: this worker's identity + lease book
    lease = health.get("lease") or {}
    if health.get("worker_id") or lease:
        lines.append(
            f"worker: {health.get('worker_id', '?')}  "
            f"leases held {len(lease.get('held', {}))}  "
            f"claims {lease.get('claims', 0)} "
            f"({lease.get('claim_lost', 0)} lost races)  "
            f"steals {lease.get('steals', 0)}  "
            f"reaped {lease.get('reaped', 0)}")
    slo = health.get("slo") or {}
    if slo:
        lines.append(
            f"slo: objectives {slo.get('objectives')}  "
            f"violations {slo.get('violations', 0)}  "
            f"burn {slo.get('burn_by_tenant')}")
    # burn-alert plane: one line per non-ok tenant (ok tenants stay
    # quiet — the alert line IS the signal), with the fast/slow
    # window ratios behind the verdict
    bplane = health.get("burn") or {}
    for t, ts in sorted((bplane.get("tenants") or {}).items()):
        state = ts.get("state", "ok")
        if state == "ok":
            continue
        fast = ts.get("fast") or {}
        slow = ts.get("slow") or {}
        lines.append(
            f"burn ALERT [{state.upper()}] tenant {t}: "
            f"fast {fast.get('violated', 0)}/{fast.get('evaluated', 0)} "
            f"({100.0 * (fast.get('ratio') or 0):.0f}%)  "
            f"slow {slow.get('violated', 0)}/{slow.get('evaluated', 0)} "
            f"({100.0 * (slow.get('ratio') or 0):.0f}%)")
    # evidence-only fleet scale hint (observability/ratecard.py)
    hint = health.get("scale_hint") or {}
    if hint:
        drain = hint.get("projected_drain_sec")
        lines.append(
            f"scale hint: {hint.get('verdict', '?')} "
            f"{hint.get('delta', 0):+d} worker(s)  "
            f"[{hint.get('reason', '')}]  "
            f"drain {_age_fmt(drain) if drain is not None else '?'} "
            f"@ {hint.get('jobs_per_sec', 0):.3g} jobs/s "
            f"({hint.get('confident_cards', 0)} confident card(s))")
    # continuous batching: prefer the live exposition gauges
    # (s2c_batch_* family), fall back to the health snapshot's batch
    # section when no exposition is wired
    bsize = _sample(samples, "s2c_batch_size")
    bocc = _sample(samples, "s2c_batch_occupancy_pct")
    bjps = _sample(samples, "s2c_batch_jobs_per_sec")
    bat = health.get("batch") or {}
    if bsize is None and bat:
        bsize = bat.get("last_size")
        bocc = bat.get("last_occupancy_pct")
        bjps = bat.get("last_jobs_per_sec")
    if bsize is not None or bat:
        npacked = _sample(samples, "s2c_batch_packed_jobs_total")
        if npacked is None:
            npacked = bat.get("packed_jobs", 0)
        lines.append(
            f"batching: size {int(bsize or 0)}  "
            f"occupancy {0.0 if bocc is None else bocc:.1f}%  "
            f"{0.0 if bjps is None else bjps:.1f} packed-jobs/s  "
            f"({int(npacked or 0)} packed total"
            + (f", mode {bat.get('mode')}" if bat else "") + ")")
    # cohort serving (s2c_cohort_* family, falling back to the health
    # snapshot's cohort section): manifest progress in one line —
    # waves done/total, samples/s, last wave's packed occupancy
    cwd = _sample(samples, "s2c_cohort_waves_done")
    cwt = _sample(samples, "s2c_cohort_waves_total")
    csd = _sample(samples, "s2c_cohort_samples_done")
    cst = _sample(samples, "s2c_cohort_samples_total")
    cjps = _sample(samples, "s2c_cohort_jobs_per_sec")
    cocc = _sample(samples, "s2c_cohort_occupancy_pct")
    coh = health.get("cohort") or {}
    if cwd is None and coh:
        cwd = coh.get("waves_done")
        cwt = coh.get("waves_total_est")
        csd = coh.get("samples_done")
        cst = coh.get("samples_total")
        lw = coh.get("last_wave") or {}
        cjps = lw.get("jobs_per_sec")
        cocc = lw.get("occupancy_pct")
    if cwd is not None or coh:
        lines.append(
            f"cohort: wave {int(cwd or 0)}/{int(cwt or 0)}  "
            f"samples {int(csd or 0)}/{int(cst or 0)}  "
            f"{0.0 if cjps is None else cjps:.1f} samples/s  "
            f"occupancy {0.0 if cocc is None else cocc:.1f}%")
    # incremental count cache (s2c_cache_* family, falling back to the
    # health snapshot's count_cache section when no exposition is wired)
    cent = _sample(samples, "s2c_cache_entries")
    cbytes = _sample(samples, "s2c_cache_resident_bytes")
    chits = _sample(samples, "s2c_cache_hits_total")
    cevict = _sample(samples, "s2c_cache_evictions_total")
    cc = health.get("count_cache") or {}
    if cent is None and cc:
        cent = cc.get("entries")
        cbytes = (cc.get("resident_mb") or 0.0) * 1e6
        chits = cc.get("hits")
        cevict = cc.get("evictions")
    if cent is not None or cc:
        lines.append(
            f"count cache: {int(cent or 0)} entr"
            f"{'y' if int(cent or 0) == 1 else 'ies'}  "
            f"{(cbytes or 0.0) / 1e6:.1f} MB resident  "
            f"{int(chits or 0)} hits  {int(cevict or 0)} evictions"
            + (f"  (budget {cc.get('budget_mb')} MB)"
               if cc.get("budget_mb") else ""))
    # streaming sessions (health "sessions" section, falling back to
    # the s2c_session_* exposition family): the live-ingest plane's
    # one-line answer — open sessions, wave flow, backlog, stability
    ses = health.get("sessions") or {}
    sopen = ses.get("open")
    if sopen is None:
        sopen = _sample(samples, "s2c_session_open")
    if sopen is not None or ses:
        sabs = ses.get("waves_absorbed")
        if sabs is None:
            sabs = _sample(samples, "s2c_session_waves_absorbed_total")
        srej = ses.get("waves_rejected")
        if srej is None:
            srej = _sample(samples, "s2c_session_waves_rejected_total")
        spend = ses.get("pending")
        if spend is None:
            spend = _sample(samples, "s2c_session_pending_waves")
        ssteal = ses.get("steals")
        if ssteal is None:
            ssteal = _sample(samples, "s2c_session_steals_total")
        age = ses.get("last_wave_age_sec")
        lines.append(
            f"sessions: {int(sopen or 0)} open "
            f"({int(ses.get('stable', 0) or 0)} stable)  "
            f"waves {int(sabs or 0)} absorbed / "
            f"{int(srej or 0)} rejected  "
            f"pending {int(spend or 0)}  steals {int(ssteal or 0)}"
            + (f"  last wave {_age_fmt(age)} ago"
               if age is not None else ""))
    # memory plane (health "memory" section, falling back to the
    # s2c_mem_* exposition family): tracked live/peak, process RSS,
    # device bytes, the capacity-shed tally and the count cache's
    # eviction pressure — the line that answers "is this server about
    # to OOM" without a Prometheus stack
    mem = health.get("memory") or {}
    tracked = mem.get("tracked") or {}
    wm = mem.get("watermarks") or {}
    live = tracked.get("live_bytes")
    if live is None:
        live = _sample(samples, "s2c_mem_live_tracked_bytes")
    peak = tracked.get("peak_bytes")
    rss = wm.get("rss_mb")
    if rss is None:
        rss = _sample(samples, "s2c_mem_rss_mb")
    prss = wm.get("peak_rss_mb")
    if prss is None:
        prss = _sample(samples, "s2c_mem_peak_rss_mb")
    cev = _sample(samples, "s2c_cache_evicted_bytes_total")
    if cev is None:
        cev = (cc.get("evicted_mb") or 0.0) * 1e6 if cc else None
    ncap = health.get("admission", {}).get("capacity")
    if live is not None or mem:
        dev = wm.get("device_bytes_in_use")
        line = (f"memory: tracked {(live or 0) / 1e6:.1f} MB live"
                + (f" / {peak / 1e6:.1f} MB peak"
                   if peak is not None else "")
                + (f"  rss {rss:.0f} MB" if rss is not None else "")
                + (f" (peak {prss:.0f})" if prss is not None else "")
                + (f"  device {dev / 1e6:.1f} MB"
                   if dev is not None else "")
                + (f"  budget {mem.get('mem_budget_mb')} MB"
                   if mem.get("mem_budget_mb") else "")
                + (f"  {int(ncap)} capacity-shed" if ncap else "")
                + (f"  cache evicted {cev / 1e6:.1f} MB" if cev else ""))
        lines.append(line)
    if mem.get("oom_dumps"):
        last = (mem.get("last_oom_dump") or {}).get("path")
        lines.append(f"OOM forensics: {mem['oom_dumps']} dump(s)"
                     + (f" (last: {last})" if last else ""))
    # mesh plane (health "mesh" section, falling back to the
    # s2c_mesh_* exposition family): hosts x shards topology, the
    # capacity plan's verdict and the shard/gather traffic — the line
    # that answers "is this job actually spanning the mesh"
    mesh = health.get("mesh") or {}
    mhosts = mesh.get("hosts")
    if mhosts is None:
        mhosts = _sample(samples, "s2c_mesh_hosts")
    mshards = mesh.get("shards")
    if mshards is None:
        mshards = _sample(samples, "s2c_mesh_shards")
    if mesh or (mshards or 0) > 1 or (mhosts or 0) > 1:
        mgather = mesh.get("gather_bytes")
        if mgather is None:
            mgather = _sample(samples, "s2c_mesh_gather_bytes_total")
        msbytes = mesh.get("shard_bytes_by_host") or {}
        planned = mesh.get("planned_hosts")
        nmesh = mesh.get("admitted_mesh")
        line = (f"mesh: {int(mhosts or 1)} host(s) x "
                f"{int(mshards or 0)} shard(s)"
                + (f"  planned {int(planned)} hosts"
                   if planned else "")
                + (f"  {int(nmesh)} mesh-admitted" if nmesh else "")
                + (f"  shard {sum(msbytes.values()) / 1e6:.1f} MB"
                   if msbytes else "")
                + (f"  gather {mgather / 1e6:.1f} MB"
                   if mgather else ""))
        lines.append(line)
    # per-tenant table from the exposition (p50/p99 e2e + rung)
    rungs = health.get("tenant_rungs", {})
    tenants = _tenants(samples) or sorted(rungs) or []
    if tenants:
        lines.append(f"{'tenant':<14} {'rung':<10} {'e2e p50':>9} "
                     f"{'e2e p99':>9} {'queue p99':>10} {'viol':>5}")
        for t in tenants:
            p50 = _sample(samples, "s2c_slo_phase_seconds", tenant=t,
                          phase="e2e", quantile="0.5")
            p99 = _sample(samples, "s2c_slo_phase_seconds", tenant=t,
                          phase="e2e", quantile="0.99")
            q99 = _sample(samples, "s2c_slo_phase_seconds", tenant=t,
                          phase="queue_wait", quantile="0.99")
            viol = sum(s["value"] for s in samples or ()
                       if s["name"] == "s2c_slo_violations_total"
                       and s["labels"].get("tenant") == t)
            lines.append(
                f"{t:<14} {rungs.get(t, 'device'):<10} "
                f"{'-' if p50 is None else f'{p50:9.3f}'} "
                f"{'-' if p99 is None else f'{p99:9.3f}'} "
                f"{'-' if q99 is None else f'{q99:10.3f}'} "
                f"{int(viol):>5}")
    drift = _sample(samples, "s2c_drift_events_total")
    if drift:
        lines.append(f"drift events: {int(drift)} (see residual/* in "
                     f"the job manifests)")
    tel = health.get("telemetry") or {}
    if tel.get("profile_captures"):
        lines.append(f"profiler captures: {tel['profile_captures']} "
                     f"(last: {tel.get('last_profile')})")
    jr = health.get("journal")
    if jr:
        lines.append(f"journal: {jr}")
    return lines


def stale_workers(healths, now=None):
    """``{path: age_sec}`` for snapshot files older than 3x their
    worker's own telemetry interval (the health ``sched`` section
    carries it; absent -> the telemetry default).  A live worker
    rewrites its snapshot every interval, so a file this old means the
    worker died, wedged, or lost its disk — the fleet frame must say
    so instead of rendering minutes-old numbers as current."""
    from sam2consensus_tpu.observability.telemetry import \
        DEFAULT_INTERVAL_S

    now = time.time() if now is None else now
    out = {}
    for path, h in healths:
        interval = ((h or {}).get("sched") or {}).get(
            "telemetry_interval_sec") or DEFAULT_INTERVAL_S
        try:
            age = now - os.path.getmtime(path)
        except OSError:
            continue
        if age > 3.0 * interval:
            out[path] = age
    return out


def render_fleet(healths, samples, now=None, stale=None):
    """One aggregated fleet frame from N workers' health snapshots
    (``[(path, dict-or-None), ...]``) plus their merged worker-labeled
    exposition samples (pure — pinned by tests).  ``stale`` is
    :func:`stale_workers`'s ``{path: age_sec}`` map; listed workers
    render a ``stale`` flag instead of passing off old numbers as
    live."""
    stale = stale or {}
    live = [(p, h) for p, h in healths if h]
    if not live:
        return ["s2c_top: waiting for fleet health snapshots..."]
    lines = []
    jobs = sum(h.get("jobs", {}).get("run", 0) for _, h in live)
    failed = sum(h.get("jobs", {}).get("failed", 0) for _, h in live)
    queue = sum(h.get("queue_depth", 0) for _, h in live)
    held = sum(len((h.get("lease") or {}).get("held", {}))
               for _, h in live)
    reaped = sum((h.get("lease") or {}).get("reaped", 0)
                 for _, h in live)
    steals = sum((h.get("lease") or {}).get("steals", 0)
                 for _, h in live)
    lost = sum((h.get("lease") or {}).get("lease_lost", 0)
               for _, h in live)
    lines.append(
        f"s2c fleet  {len(healths)} worker(s) ({len(live)} reporting"
        + (f", {len(stale)} stale" if stale else "") + ")"
        f"  queue {queue}  jobs {jobs} ({failed} failed)  "
        f"leases held {held}, reaped {reaped}, stolen {steals}"
        + (f", lost {lost}" if lost else ""))
    lines.append(f"{'worker':<12} {'up':>7} {'queue':>5} "
                 f"{'in-flight':<26} {'hb-age':>7} {'leases':>6} "
                 f"{'jobs':>5}")
    for path, h in sorted(healths,
                          key=lambda ph: (ph[1] or {}).get(
                              "worker_id") or ph[0]):
        wid = (h or {}).get("worker_id") \
            or os.path.basename(path)
        if h is None:
            lines.append(f"{wid:<12} {'-':>7}  (no snapshot yet)")
            continue
        hb = h.get("last_heartbeat_age_sec")
        inflight = h.get("in_flight")
        flag = " <<wedge?" if inflight and hb is not None \
            and hb > 5.0 else ""
        if path in stale:
            flag = (f" <<stale: snapshot {_age_fmt(stale[path])} old"
                    f"{flag}")
        infl = "-"
        if inflight:
            infl = (f"{inflight[:18]} "
                    f"({_age_fmt(h.get('in_flight_sec'))})")
        lines.append(
            f"{wid:<12} {_age_fmt(h.get('uptime_sec')):>7} "
            f"{h.get('queue_depth', 0):>5} {infl:<26} "
            f"{_age_fmt(hb):>7} "
            f"{len((h.get('lease') or {}).get('held', {})):>6} "
            f"{h.get('jobs', {}).get('run', 0):>5}{flag}")
    # merged per-tenant SLO burn from the health side (the exposition
    # table below carries the latency quantiles when wired)
    burn = {}
    for _, h in live:
        for t, n in ((h.get("slo") or {}).get("burn_by_tenant")
                     or {}).items():
            burn[t] = burn.get(t, 0) + n
    if burn:
        lines.append(f"slo burn by tenant (all workers): {burn}")
    # worst burn-alert state + scale hint per worker (evidence plane)
    paging = {}
    for wid, h in live:
        for t, ts in (((h.get("burn") or {}).get("tenants"))
                      or {}).items():
            st = ts.get("state", "ok")
            if st != "ok":
                cur = paging.get(t)
                if cur is None or (st == "page" and cur != "page"):
                    paging[t] = st
    if paging:
        lines.append("burn alerts: " + "  ".join(
            f"{t}={s.upper()}" for t, s in sorted(paging.items())))
    hints = [(wid, h.get("scale_hint")) for wid, h in live
             if h.get("scale_hint")]
    if hints:
        # any worker arguing "up" wins the merged line (conservative:
        # never under-report pressure); ties go to the latest worker
        best = None
        for wid, hint in hints:
            if best is None or hint.get("verdict") == "up":
                best = (wid, hint)
        wid, hint = best
        lines.append(
            f"scale hint ({wid}): {hint.get('verdict', '?')} "
            f"{hint.get('delta', 0):+d} worker(s) "
            f"[{hint.get('reason', '')}]")
    tenants = _tenants(samples)
    if tenants:
        lines.append(f"{'tenant':<14} {'e2e p99 by worker':<40} "
                     f"{'viol':>5}")
        for t in tenants:
            per_w = {}
            for s in samples or ():
                if s["name"] == "s2c_slo_phase_seconds" \
                        and s["labels"].get("tenant") == t \
                        and s["labels"].get("phase") == "e2e" \
                        and s["labels"].get("quantile") == "0.99":
                    per_w[s["labels"].get("worker", "?")] = s["value"]
            viol = sum(s["value"] for s in samples or ()
                       if s["name"] == "s2c_slo_violations_total"
                       and s["labels"].get("tenant") == t)
            cells = "  ".join(f"{w}={v:.3f}s"
                              for w, v in sorted(per_w.items()))
            lines.append(f"{t:<14} {cells:<40} {int(viol):>5}")
    # every worker shares ONE journal: show it once
    jr = next((h.get("journal") for _, h in live
               if h.get("journal")), None)
    if jr:
        lines.append(f"journal: {jr}")
    return lines


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--health", required=True,
                   help="the server's --health-out path (a GLOB over "
                        "worker snapshots with --fleet)")
    p.add_argument("--telemetry", default=None,
                   help="the server's --telemetry-out exposition path "
                        "(optional; adds per-tenant latency columns; "
                        "a GLOB with --fleet)")
    p.add_argument("--fleet", action="store_true",
                   help="aggregate N workers' health/exposition files "
                        "(--health/--telemetry become globs) into one "
                        "fleet frame")
    p.add_argument("--interval", type=float, default=2.0,
                   help="poll period in seconds (default 2)")
    p.add_argument("--once", action="store_true",
                   help="render one frame and exit (CI logs, tests)")
    args = p.parse_args(argv)

    import glob as _glob

    while True:
        if args.fleet:
            hpaths = sorted(_glob.glob(args.health)) or [args.health]
            healths = [(pth, read_health(pth)) for pth in hpaths]
            samples = []
            for pth in sorted(_glob.glob(args.telemetry or "")):
                samples.extend(read_telemetry(pth) or [])
            frame = render_fleet(healths, samples or None,
                                 stale=stale_workers(healths))
        else:
            health = read_health(args.health)
            samples = read_telemetry(args.telemetry) \
                if args.telemetry else None
            frame = render(health, samples)
        if args.once:
            print("\n".join(frame))
            return 0
        sys.stdout.write("\x1b[2J\x1b[H")     # clear + home, no curses
        sys.stdout.write("\n".join(frame) + "\n")
        sys.stdout.write(f"\n[{time.strftime('%H:%M:%S')}] "
                         f"polling every {args.interval:g}s "
                         f"(ctrl-c to quit)\n")
        sys.stdout.flush()
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
