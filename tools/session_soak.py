#!/usr/bin/env python3
"""Streaming-session chaos soak: SIGKILL/SIGSTOP/fault cycles against
live ingest, asserting byte-identity and exactly-once wave accounting.

Each cycle simulates a basecaller feeding one journaled streaming
session (``s2c serve --journal DIR --ingest-port 0``) in read WAVES
over the HTTP front door, then murders the serving worker mid-session:

* ``kill``  — SIGKILL the worker after some waves are absorbed and
  others are journaled-but-pending; a peer worker must steal the
  session lease, replay every uncovered wave from its spool, and keep
  serving the SAME sid to the retargeted client;
* ``wedge`` — SIGSTOP instead (a zombie holding a live socket); the
  peer must still take over once the lease TTL lapses, and the frozen
  victim is reaped at cycle end without ever double-absorbing;
* ``fault`` — no signals; the worker runs with an injected
  ``session_wave_append`` fault (the crash window between the durable
  ``wave_received`` intent and the ``wave_absorbed`` commit) so the
  count-bank rule's invalidate-and-replay path fires mid-soak.

Invariants asserted per cycle (any miss is a cycle failure):

* the final per-reference FASTA content is byte-identical to a
  ONE-SHOT batch run over the concatenated waves (same RunConfig);
* the journal audit shows 0 lost and 0 duplicated waves for the sid;
* kill/wedge cycles: the peer's re-claim lands within 2x lease TTL
  (measured from journal event timestamps), and every wave posted
  before the signal is absorbed by the thief before new waves land.

Emits one JSONL row per cycle plus a ``summary`` row; commit the
output as ``campaign/session_soak_*.jsonl`` and cite it from PERF.md
(tools/check_perf_claims.py lints the citation).

Usage::

    python tools/session_soak.py --cycles 3 --waves 6 --out soak.jsonl
"""

import argparse
import hashlib
import http.client
import json
import os
import platform
import re
import shutil
import signal
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

MODES = ("kill", "wedge", "fault")
PORT_RE = re.compile(r"127\.0\.0\.1:(\d+)")
DEFAULT_FAULT_SPEC = ("session_wave_append:rpc:1:2,"
                      "session_wave_append:rpc:6:1")


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def sha256_hex(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


# ---------------------------------------------------------------------------
# corpus: one simulated SAM split into a header + contiguous read waves
# ---------------------------------------------------------------------------

def build_corpus(args, work):
    """Returns (header_text, [wave_body_bytes...], concat_sam_path)."""
    from sam2consensus_tpu.utils.simulate import SimSpec, simulate

    spec = SimSpec(n_contigs=2, contig_len=args.contig_len,
                   n_reads=args.reads, read_len=args.read_len,
                   contig_len_jitter=0.0, seed=8200,
                   contig_prefix="ss_")
    text = simulate(spec)
    lines = text.splitlines(keepends=True)
    header = "".join(l for l in lines if l.startswith("@"))
    reads = [l for l in lines if not l.startswith("@")]
    waves = []
    per = max(1, (len(reads) + args.waves - 1) // args.waves)
    for i in range(0, len(reads), per):
        waves.append("".join(reads[i:i + per]).encode("utf-8"))
    concat = os.path.join(work, "corpus.sam")
    with open(concat, "w") as fh:
        fh.write(text)
    return header, waves, concat


def baseline_shas(concat, work):
    """{reference -> sha256(file content)} from a one-shot in-process
    batch run with the SAME RunConfig the session servers use (prefix
    "" — session mode has no -p flag, and the prefix is baked into
    every FASTA header, so the oracle must match it)."""
    from sam2consensus_tpu.config import RunConfig
    from sam2consensus_tpu.io.fasta import write_outputs
    from sam2consensus_tpu.serve import JobSpec, ServeRunner

    outdir = os.path.join(work, "out_base")
    os.makedirs(outdir, exist_ok=True)
    noop = lambda *a, **k: None  # noqa: E731
    runner = ServeRunner(prewarm="off", decode_ahead=False, echo=noop)
    try:
        res = runner.submit_jobs(
            [JobSpec(filename=concat,
                     config=RunConfig(prefix="",
                                      outfolder=outdir + os.sep),
                     job_id="baseline")])[0]
        if res.error or res.fastas is None:
            raise RuntimeError(f"baseline job failed: {res.error}")
        paths = write_outputs(res.fastas, outdir + os.sep, "", 0,
                              [0.25], echo=noop)
    finally:
        runner.close()
    return ref_shas(paths)


def ref_shas(paths):
    """{reference -> content sha} (filenames differ between baseline
    and session outputs — ``{ref}__{prefix-or-sid}.fasta`` — so the
    comparison is keyed on the reference name, valued on CONTENT)."""
    out = {}
    for p in paths:
        ref = os.path.basename(p).split("__")[0]
        with open(p, "rb") as fh:
            out[ref] = sha256_hex(fh.read())
    return out


# ---------------------------------------------------------------------------
# workers: real CLI server subprocesses with ephemeral ingest ports
# ---------------------------------------------------------------------------

def worker_cmd(jdir, worker, ttl, debounce, extra=()):
    return [sys.executable, "-m", "sam2consensus_tpu.cli", "serve",
            "--journal", jdir, "--ingest-port", "0",
            "--worker-id", worker, "--lease-ttl", str(ttl),
            "--revote-debounce", str(debounce),
            "--stability-waves", "3", *extra]


class Worker:
    """One server subprocess + a stdout reader thread (the ingest port
    is announced on stdout; the thread also keeps the pipe drained)."""

    def __init__(self, name, cmd, env, work):
        self.name = name
        self.errpath = os.path.join(work, f"{name}.stderr")
        self._errfh = open(self.errpath, "w")
        self.proc = subprocess.Popen(cmd, env=env,
                                     stdout=subprocess.PIPE,
                                     stderr=self._errfh, text=True)
        self.lines = []
        self._t = threading.Thread(target=self._drain, daemon=True)
        self._t.start()

    def _drain(self):
        try:
            for line in self.proc.stdout:
                self.lines.append(line)
        except (ValueError, OSError):
            pass

    def port(self, timeout):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for line in list(self.lines):
                m = PORT_RE.search(line)
                if m:
                    return int(m.group(1))
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"worker {self.name} exited rc="
                    f"{self.proc.returncode} before announcing a port "
                    f"(stderr: {self.errpath})")
            time.sleep(0.02)
        raise RuntimeError(f"worker {self.name}: no ingest port within "
                           f"{timeout:g}s (stderr: {self.errpath})")

    def reap(self, timeout=30.0):
        if self.proc.poll() is None:
            try:
                self.proc.send_signal(signal.SIGCONT)  # un-wedge first
            except OSError:
                pass
            self.proc.terminate()
            try:
                self.proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=10)
        try:
            self.proc.stdout.close()
        except (ValueError, OSError):
            pass
        self._errfh.close()


# ---------------------------------------------------------------------------
# HTTP client helpers (stdlib only — same dependency budget as the server)
# ---------------------------------------------------------------------------

def api(port, method, path, body=b"", headers=None, timeout=30.0):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request(method, path, body=body, headers=headers or {})
        resp = conn.getresponse()
        data = resp.read()
        try:
            payload = json.loads(data.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            payload = {}
        return resp.status, payload
    finally:
        conn.close()


def post_wave(port, sid, body, deadline):
    """POST one wave with its integrity sha; retries 429 backpressure
    and 5xx until ``deadline``.  Returns the final ACK payload, or
    None if the worker died (connection refused/reset) — the caller
    retargets to the surviving peer."""
    headers = {"X-Wave-Sha256": "sha256:" + sha256_hex(body)}
    while True:
        try:
            status, payload = api(port, "POST",
                                  f"/session/{sid}/wave", body=body,
                                  headers=headers)
        except OSError:
            return None
        if status in (200, 202):
            return payload
        if time.monotonic() >= deadline:
            raise RuntimeError(
                f"wave POST stuck at HTTP {status}: {payload}")
        if status in (429, 500, 503):
            time.sleep(float(payload.get("retry_after") or 0.2))
            continue
        raise RuntimeError(f"wave POST rejected: HTTP {status} "
                           f"{payload}")


def poll_status(port, sid, want, deadline, allow_dead=False):
    """Poll GET /session/<sid> until ``want(status_payload)`` is true."""
    last = None
    while time.monotonic() < deadline:
        try:
            status, payload = api(port, "GET", f"/session/{sid}")
        except OSError:
            if not allow_dead:
                raise
            status, payload = None, {}
        last = (status, payload)
        if status == 200 and want(payload):
            return payload
        time.sleep(0.05)
    raise RuntimeError(f"session {sid}: condition not reached "
                       f"(last: {last})")


# ---------------------------------------------------------------------------
# journal forensics
# ---------------------------------------------------------------------------

def journal_events(jdir):
    from sam2consensus_tpu.serve.journal import JobJournal

    if not os.path.isdir(jdir):
        return []
    try:
        return JobJournal(jdir, checkpoint_every=0).events()
    except OSError:
        return []


def steal_latency(jdir, sid, victim, t_signal):
    """Seconds from the chaos signal to a peer's re-claim of the
    session lease (journal event wall-clock timestamps)."""
    for e in journal_events(jdir):
        if e.get("ev") == "claimed" and e.get("key") == sid \
                and e.get("worker") != victim \
                and float(e.get("t", 0)) >= t_signal:
            return round(float(e["t"]) - t_signal, 3)
    return None


def session_audit(jdir, sid):
    from sam2consensus_tpu.serve.journal import JobJournal

    audit = JobJournal(jdir, checkpoint_every=0).audit(full=True)
    return (audit.get("sessions") or {}).get(sid) or {}


# ---------------------------------------------------------------------------
# one chaos cycle
# ---------------------------------------------------------------------------

def run_cycle(c, mode, args, header, waves, want, env, work):
    jdir = os.path.join(work, f"j_c{c}")
    shutil.rmtree(jdir, ignore_errors=True)
    deadline = time.monotonic() + args.per_process_timeout
    names = ("sv0", "sv1")
    victim_name, peer_name = names
    workers = {}
    t_cycle = time.monotonic()
    errors = []
    row = {"kind": "cycle", "cycle": c, "mode": mode}
    try:
        for i, name in enumerate(names):
            extra = ()
            if mode == "fault" and i == 0:
                extra = ("--fault-inject", args.fault_spec)
            workers[name] = Worker(
                name, worker_cmd(jdir, name, args.lease_ttl,
                                 args.revote_debounce, extra),
                env, work)
        ports = {n: w.port(args.per_process_timeout)
                 for n, w in workers.items()}

        status, payload = api(ports[victim_name], "POST",
                              "/session/open",
                              body=header.encode("utf-8"),
                              headers={"X-Tenant": "soak"})
        if status != 200:
            raise RuntimeError(f"open failed: HTTP {status} {payload}")
        sid = payload["sid"]
        row["sid"] = sid

        # phase 1: feed the victim.  First ``j`` waves are allowed to
        # absorb fully (so a checkpoint exists to re-seed from); the
        # next chunk is posted back-to-back inside the debounce window
        # so the signal lands on a journaled-but-unabsorbed backlog —
        # the exact window the replay machinery exists for.
        k_signal = len(waves) if mode == "fault" \
            else max(2, len(waves) * 2 // 3)
        j = max(1, k_signal // 2)
        for n in range(j):
            if post_wave(ports[victim_name], sid, waves[n],
                         deadline) is None:
                raise RuntimeError("victim died before the signal")
        poll_status(ports[victim_name], sid,
                    lambda s: s["absorbed"] >= j, deadline)
        for n in range(j, k_signal):
            if post_wave(ports[victim_name], sid, waves[n],
                         deadline) is None:
                raise RuntimeError("victim died before the signal")

        steal_sec = None
        serve_port = ports[victim_name]
        if mode in ("kill", "wedge"):
            t_signal = time.time()
            workers[victim_name].proc.send_signal(
                signal.SIGKILL if mode == "kill" else signal.SIGSTOP)
            log(f"[session_soak] c{c} {mode}: "
                f"{'killed' if mode == 'kill' else 'froze'} "
                f"{victim_name} with {k_signal - j} wave(s) pending")
            # retarget the client: the peer adopts the orphaned
            # session once the lease TTL lapses, replays the
            # journaled-but-unabsorbed waves from their spools, and
            # answers the same sid
            serve_port = ports[peer_name]
            st = poll_status(serve_port, sid,
                             lambda s: s["absorbed"] >= k_signal,
                             deadline)
            if st.get("stolen_from") != victim_name:
                errors.append(f"thief reports stolen_from="
                              f"{st.get('stolen_from')!r}, expected "
                              f"{victim_name!r}")
            steal_sec = steal_latency(jdir, sid, victim_name, t_signal)
            if steal_sec is None:
                errors.append("no peer re-claim in the journal")
            elif steal_sec > 2 * args.lease_ttl:
                errors.append(f"steal took {steal_sec:.2f}s "
                              f"(bound {2 * args.lease_ttl:.2f}s)")

        for n in range(k_signal, len(waves)):
            if post_wave(serve_port, sid, waves[n], deadline) is None:
                raise RuntimeError("serving worker died mid-stream")
        poll_status(serve_port, sid,
                    lambda s: s["absorbed"] >= len(waves), deadline)

        status, final = api(serve_port, "POST", f"/session/{sid}/close",
                            timeout=args.per_process_timeout)
        if status != 200:
            raise RuntimeError(f"close failed: HTTP {status} {final}")

        got = ref_shas(final.get("outputs") or [])
        identical = got == want
        if not identical:
            errors.append(f"output mismatch: want {sorted(want)}, "
                          f"got {sorted(got)}")

        aud = session_audit(jdir, sid)
        if aud.get("duplicated_waves"):
            errors.append(f"duplicated waves: "
                          f"{aud['duplicated_waves']}")
        if aud.get("lost_waves"):
            errors.append(f"lost waves: {aud['lost_waves']}")
        if aud.get("absorbed") != len(waves):
            errors.append(f"absorbed {aud.get('absorbed')} of "
                          f"{len(waves)} waves")

        row.update({
            "waves": len(waves),
            "waves_before_signal": k_signal,
            "steal_sec": steal_sec,
            "steal_bound_sec": round(2 * args.lease_ttl, 3),
            "identical": identical,
            "duplicated_waves": aud.get("duplicated_waves", []),
            "lost_waves": aud.get("lost_waves", []),
            "rejected_waves": aud.get("rejected_waves", []),
            "reads_total": aud.get("reads_total"),
            "stable": bool(final.get("stable")
                           or aud.get("stable")),
            "digest": (final.get("digest") or "")[:19],
        })
    except Exception as exc:  # a dead cycle is a row, not a crash
        errors.append(f"{type(exc).__name__}: {exc}")
    finally:
        for w in workers.values():
            w.reap()
    row["elapsed_sec"] = round(time.monotonic() - t_cycle, 3)
    row["ok"] = not errors
    row["errors"] = errors
    return row


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--cycles", type=int, default=6)
    ap.add_argument("--waves", type=int, default=6)
    ap.add_argument("--reads", type=int, default=6000)
    ap.add_argument("--contig-len", type=int, default=3000)
    ap.add_argument("--read-len", type=int, default=100)
    ap.add_argument("--lease-ttl", type=float, default=2.5)
    ap.add_argument("--revote-debounce", type=float, default=0.3,
                    help="victim/peer debounce: waves ACK 202 and "
                         "absorb on the tick, so a signal can land on "
                         "a journaled-but-unabsorbed backlog")
    ap.add_argument("--fault-spec", default=DEFAULT_FAULT_SPEC)
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: a fresh tempdir)")
    ap.add_argument("--per-process-timeout", type=float, default=600.0)
    ap.add_argument("--out", default=None,
                    help="JSONL destination (default: stdout)")
    args = ap.parse_args(argv)
    if args.waves < 3:
        ap.error("--waves must be >= 3 (need absorbed + pending + "
                 "post-steal waves)")

    import tempfile

    work = args.workdir or tempfile.mkdtemp(prefix="s2c_session_")
    os.makedirs(work, exist_ok=True)
    log(f"[session_soak] workdir {work}")

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # one persistent compile cache for the whole soak: cycles measure
    # recovery + replay, not XLA re-compilation
    env["S2C_JIT_CACHE"] = os.path.join(work, "_jit_cache")
    os.environ["S2C_JIT_CACHE"] = env["S2C_JIT_CACHE"]

    header, waves, concat = build_corpus(args, work)
    log(f"[session_soak] corpus: {args.reads} reads over "
        f"{len(waves)} wave(s)")
    t0 = time.monotonic()
    want = baseline_shas(concat, work)
    log(f"[session_soak] one-shot baseline "
        f"{time.monotonic() - t0:.1f}s, {len(want)} reference(s)")

    rows = []
    failures = 0
    steals = []
    for c in range(args.cycles):
        mode = MODES[c % len(MODES)]
        row = run_cycle(c, mode, args, header, waves, want, env, work)
        rows.append(row)
        if not row["ok"]:
            failures += 1
            log(f"[session_soak] c{c} {mode} FAILED: {row['errors']}")
        else:
            extra = (f" steal {row['steal_sec']:.2f}s"
                     if row.get("steal_sec") is not None else "")
            log(f"[session_soak] c{c} {mode} ok "
                f"({row['elapsed_sec']:.1f}s{extra})")
        if row.get("steal_sec") is not None:
            steals.append(row["steal_sec"])

    summary = {
        "kind": "summary",
        "schema": "s2c-session-soak/1",
        "cycles": args.cycles,
        "waves": len(waves),
        "reads": args.reads,
        "lease_ttl_sec": args.lease_ttl,
        "steal_bound_sec": round(2 * args.lease_ttl, 3),
        "max_steal_sec": max(steals) if steals else None,
        "failures": failures,
        "identical_all": all(r.get("identical") for r in rows),
        "lost_total": sum(len(r.get("lost_waves") or [])
                          for r in rows),
        "duplicated_total": sum(len(r.get("duplicated_waves") or [])
                                for r in rows),
        "host_cores": os.cpu_count(),
        "platform": platform.platform(),
    }
    rows.append(summary)

    out = "\n".join(json.dumps(r, sort_keys=True) for r in rows) + "\n"
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(out)
    else:
        sys.stdout.write(out)
    log(f"[session_soak] SUMMARY: failures={failures} "
        f"lost={summary['lost_total']} "
        f"dup={summary['duplicated_total']} "
        f"identical_all={summary['identical_all']} "
        f"max_steal={summary['max_steal_sec']}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
