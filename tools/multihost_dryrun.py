#!/usr/bin/env python3
"""True multi-PROCESS validation of the sharded pipeline (DCN topology).

The single-process dryrun (``__graft_entry__.dryrun_multichip``) proves
the collectives on a virtual mesh inside one controller.  This harness
proves the stronger claim PERF.md §6 makes — "nothing in the code
distinguishes single-host ICI from multi-host DCN" — by actually running
the production ``parallel.dp.ShardedConsensus`` over a mesh that SPANS
OS PROCESSES: ``jax.distributed`` multi-controller, N processes x M
virtual CPU devices each, cross-process collectives over gloo (the CPU
stand-in for DCN).  Each process executes the same SPMD program; the
count tensor's shards live in different address spaces; psum_scatter /
psum run across the process boundary; ``fetch_host`` assembles results
via ``process_allgather``.

Checks (every process asserts, process 0 reports):
  * sharded counts == single-device oracle counts (exact integers);
  * sharded vote symbols == unsharded ``vote_positions``;
  * ``tail_stats`` contig sums == oracle coverage sums.

``--bench`` is the MULTICHIP measurement leg (campaign step 17): a
procs x devs sweep where each point runs the FULL production
``JaxBackend`` job over the process-spanning mesh and is compared
byte-for-byte against the in-launcher ``CpuBackend`` FASTA oracle.
Each row also carries the capacity-planned admission story end to end:
the memory plane prices the job (``plan_mesh_shards``) against a
budget deliberately set between the 1-host and 2-host per-host peaks,
the real ``AdmissionController`` issues the "needs K hosts"
``mesh_shards`` verdict, and the row joins the predicted per-host
bytes against the workers' measured tracked peak (residual must sit
inside the S2C_DRIFT_BAND).  Rows are JSONL on stdout (``--out -``
campaign idiom); worker chatter goes to stderr.

Usage:
  python tools/multihost_dryrun.py              # spawn 2 procs x 4 devs
  python tools/multihost_dryrun.py --procs 2 --devs 4
  python tools/multihost_dryrun.py --bench --sweep 1x8,2x4 --out -
  (workers are re-invocations of this script with --worker <pid>)
"""

import argparse
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _init_distributed(n_procs: int, pid: int, port: int):
    """``jax.distributed`` bring-up for one worker.  The CPU stand-in
    needs the gloo collectives implementation selected BEFORE the
    backend initializes — without it the CPU client has no
    cross-process transport and every process-spanning computation
    dies with "Multiprocess computations aren't implemented on the
    CPU backend" (the env var spelling of the option is not read on
    this jax, so it must be set via jax.config)."""
    from sam2consensus_tpu.utils.platform import pin_platform_from_env

    pin_platform_from_env()
    import jax

    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass            # non-CPU rig or the option moved; best effort
    jax.distributed.initialize(coordinator_address=f"localhost:{port}",
                               num_processes=n_procs, process_id=pid)
    return jax


def worker(pid: int, n_procs: int, n_devs: int, port: int) -> int:
    jax = _init_distributed(n_procs, pid, port)
    import numpy as np

    from sam2consensus_tpu.encoder.events import GenomeLayout, ReadEncoder
    from sam2consensus_tpu.io.sam import iter_records, read_header
    from sam2consensus_tpu.ops.cutoff import encode_thresholds
    from sam2consensus_tpu.ops.vote import vote_positions
    from sam2consensus_tpu.parallel.dp import ShardedConsensus
    from sam2consensus_tpu.parallel.mesh import make_mesh
    from sam2consensus_tpu.utils.simulate import SimSpec, simulate
    import io as _io
    import jax.numpy as jnp

    n_global = n_procs * n_devs
    assert len(jax.devices()) == n_global, \
        f"expected {n_global} global devices, got {len(jax.devices())}"
    assert len(jax.local_devices()) == n_devs

    # identical fixture on every process (same seed): multi-controller
    # SPMD requires every process to feed the same global values
    text = simulate(SimSpec(n_contigs=3, contig_len=160, n_reads=400,
                            read_len=24, max_indel=2, seed=77))
    handle = _io.StringIO(text)
    contigs, _n, first = read_header(handle)
    layout = GenomeLayout(contigs)
    enc = ReadEncoder(layout)
    batches = list(enc.encode_segments(iter_records(handle, first), 10 ** 9))

    from sam2consensus_tpu.parallel.dpsp import ProductShardedConsensus
    from sam2consensus_tpu.parallel.sp import PositionShardedConsensus

    mesh = make_mesh(n_global)
    assert mesh.size == n_global

    # oracle: single-device accumulation from the same batches
    want = np.zeros((layout.total_len, 6), dtype=np.int32)
    for b in batches:
        for _w, (starts, codes) in b.buckets.items():
            rows, cols = np.nonzero(codes != 255)
            pos = starts[rows] + cols
            ok = pos < layout.total_len
            np.add.at(want, (pos[ok], codes[rows, cols][ok]), 1)

    thr_enc = encode_thresholds([0.25, 0.75])
    syms1, cov1 = vote_positions(jnp.asarray(want), jnp.asarray(thr_enc), 1)
    want_sums = [np.asarray(cov1)[int(layout.offsets[i]):
                                  int(layout.offsets[i + 1])].sum()
                 for i in range(len(layout.names))]

    # all three production layouts over the process-spanning mesh: dp
    # (scatter + psum_scatter), sp (row routing + ppermute halo), dp x sp
    # (both axes product mode)
    modes = {
        "dp": lambda: ShardedConsensus(mesh, layout.total_len,
                                       pileup="scatter"),
        "sp": lambda: PositionShardedConsensus(mesh, layout.total_len,
                                               halo=64),
        "dpsp": lambda: ProductShardedConsensus(mesh, layout.total_len,
                                                halo=64),
    }
    for mode, build in modes.items():
        sharded = build()
        for b in batches:
            sharded.add(b)
        np.testing.assert_array_equal(sharded.counts_host(), want,
                                      err_msg=f"{mode}: counts diverge")
        syms = sharded.vote(thr_enc, min_depth=1)
        np.testing.assert_array_equal(syms, np.asarray(syms1),
                                      err_msg=f"{mode}: vote diverges")
        contig_sums, _ = sharded.tail_stats(
            layout.offsets.astype(np.int32), np.zeros(0, dtype=np.int32))
        np.testing.assert_array_equal(contig_sums, want_sums,
                                      err_msg=f"{mode}: stats diverge")
        if pid == 0:
            print(f"  [{mode}] counts+vote+stats byte-equal", flush=True)

    if pid == 0:
        print(f"MULTIHOST OK: {n_procs} processes x {n_devs} devices, "
              f"dp/sp/dpsp byte-equal across the process-spanning mesh",
              flush=True)
    return 0


# =====================================================================
# --bench: the MULTICHIP measurement leg
# =====================================================================
#: the bench fixture — a wide_genome-class shape: the genome is wide
#: enough that the count/tail planes dominate staging in the capacity
#: model (so per_host(2) is a real cut below per_host(1) and the
#: tracked-counts measurement can sit inside the drift band of the
#: per-host prediction) while still finishing on the one-core gloo
#: stand-in inside the shared deadline
BENCH_SIM = dict(n_contigs=4, contig_len=24000, n_reads=1200,
                 read_len=60, max_indel=2, seed=101)
BENCH_THRESHOLDS = [0.25, 0.75]
#: staging chunk pinned to the fixture's scale: the capacity model
#: prices the configured chunk geometry, so leaving the 262144-read
#: default would predict ~30x the slab bytes this fixture ever stages
#: and push the mesh_shards residual out of the drift band for the
#: wrong reason (model/config mismatch, not model error)
BENCH_CHUNK_READS = 2048


def _bench_fixture() -> str:
    from sam2consensus_tpu.utils.simulate import SimSpec, simulate

    return simulate(SimSpec(**BENCH_SIM))


def _rendered(backend, text: str, cfg) -> dict:
    """{ref_name: full FASTA file text} — the byte-identity surface the
    differential suite gates on (tests/test_differential.py)."""
    import io as _io

    from sam2consensus_tpu.io.fasta import render_file
    from sam2consensus_tpu.io.sam import iter_records, read_header

    handle = _io.StringIO(text)
    contigs, _n, first = read_header(handle)
    res = backend.run(contigs, iter_records(handle, first), cfg)
    return {name: render_file(recs, cfg.nchar)
            for name, recs in res.fastas.items()}


def _fasta_sha(rendered: dict) -> str:
    import hashlib

    h = hashlib.sha256()
    for name in sorted(rendered):
        h.update(name.encode())
        h.update(b"\x00")
        h.update(rendered[name].encode())
    return h.hexdigest()


def bench_worker(pid: int, n_procs: int, n_devs: int, port: int,
                 oracle_sha: str) -> int:
    """One bench process: full ``JaxBackend`` job over the
    process-spanning mesh, FASTA hash vs the launcher's CPU oracle,
    mesh/memory counters read back from the run's metrics JSONL.
    Emits one ``BENCHJSON {...}`` line (every pid — the launcher sums
    per-host shard bytes and cross-checks hash agreement)."""
    import json
    import tempfile
    import time

    jax = _init_distributed(n_procs, pid, port)
    from sam2consensus_tpu.backends.jax_backend import JaxBackend
    from sam2consensus_tpu.config import RunConfig
    from sam2consensus_tpu.observability import memplane
    from sam2consensus_tpu.observability.export import read_metrics_jsonl

    n_global = n_procs * n_devs
    assert len(jax.devices()) == n_global
    text = _bench_fixture()
    fd, metrics_path = tempfile.mkstemp(prefix="s2c_meshbench_",
                                        suffix=".jsonl")
    os.close(fd)
    cfg = RunConfig(thresholds=list(BENCH_THRESHOLDS), prefix="bench",
                    backend="jax", shards=n_global,
                    chunk_reads=BENCH_CHUNK_READS,
                    metrics_out=metrics_path)
    t0 = time.perf_counter()
    rendered = _rendered(JaxBackend(), text, cfg)
    wall = time.perf_counter() - t0
    sha = _fasta_sha(rendered)

    counters, gauges = {}, {}
    try:
        for row in read_metrics_jsonl(metrics_path):
            if row.get("kind") == "counter":
                counters[row["name"]] = row["value"]
            elif row.get("kind") == "gauge":
                gauges[row["name"]] = row["value"]
    finally:
        try:
            os.unlink(metrics_path)
        except OSError:
            pass
    payload = {
        "pid": pid,
        "wall_sec": round(wall, 4),
        "fasta_sha": sha,
        "identical_fasta": sha == oracle_sha,
        "hosts": int(gauges.get("mesh/hosts", 1)),
        "shards": int(gauges.get("mesh/shards", n_global)),
        "shard_bytes": int(counters.get(f"mesh/shard_bytes/{pid}", 0)),
        "gather_bytes": int(counters.get("mesh/gather_bytes", 0)),
        "h2d_bytes": int(counters.get("wire/h2d_bytes", 0)),
        "d2h_bytes": int(counters.get("wire/d2h_bytes", 0)),
        "peak_tracked_bytes":
            int(memplane.summary()["tracked"]["peak_bytes"]),
    }
    print("BENCHJSON " + json.dumps(payload), flush=True)
    return 0


def _spawn_workers(n_procs: int, n_devs: int, port: int,
                   extra_argv=(), deadline_sec: float = 480.0):
    """Spawn N worker re-invocations; returns (rcs, outs, timed_out).

    Each worker gets its own process group (start_new_session) so a
    hang can be killed wholesale; one drain thread per pipe so a
    worker writing a large failure traceback can never block on a
    full unread pipe while the launcher waits on another worker.  One
    SHARED deadline across all joins (sequential per-thread timeouts
    would sum to procs x deadline and outlive the suite test's outer
    timeout, leaking killed-launcher worker groups)."""
    import signal
    import threading
    import time

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                        f" --xla_force_host_platform_device_count="
                        f"{n_devs}").strip()
    procs = [subprocess.Popen(
        [sys.executable, os.path.abspath(__file__),
         "--worker", str(i), "--procs", str(n_procs),
         "--devs", str(n_devs), "--port", str(port),
         *extra_argv],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        start_new_session=True)
        for i in range(n_procs)]
    outs = [b""] * n_procs

    def drain(i):
        outs[i] = procs[i].communicate()[0]

    threads = [threading.Thread(target=drain, args=(i,), daemon=True)
               for i in range(n_procs)]
    for t in threads:
        t.start()
    end = time.monotonic() + deadline_sec
    for t in threads:
        t.join(timeout=max(0.0, end - time.monotonic()))
    timed_out = any(t.is_alive() for t in threads)
    if timed_out:
        for p in procs:
            if p.poll() is None:
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
        for t in threads:
            t.join(timeout=10)
    return [p.poll() for p in procs], outs, timed_out


def run_bench(args) -> int:
    """The launcher side of ``--bench``: oracle once, then per sweep
    point the capacity/admission leg + the distributed measurement."""
    import json
    import time

    from sam2consensus_tpu.backends.cpu import CpuBackend
    from sam2consensus_tpu.config import RunConfig
    from sam2consensus_tpu.io.sam import read_header
    from sam2consensus_tpu.observability import memplane
    from sam2consensus_tpu.serve.admission import AdmissionController

    band = float(os.environ.get("S2C_DRIFT_BAND", "4"))
    out = sys.stdout if args.out in (None, "-") \
        else open(args.out, "w", encoding="utf-8")

    def emit(row):
        out.write(json.dumps(row) + "\n")
        out.flush()

    text = _bench_fixture()
    import io as _io

    contigs, _n, _first = read_header(_io.StringIO(text))
    total_len = sum(c.length for c in contigs)
    cfg_cpu = RunConfig(thresholds=list(BENCH_THRESHOLDS),
                        prefix="bench",
                        chunk_reads=BENCH_CHUNK_READS)
    print("bench: rendering CPU oracle...", file=sys.stderr, flush=True)
    oracle_sha = _fasta_sha(_rendered(CpuBackend(), text, cfg_cpu))

    sweep = []
    for leg in (args.sweep or "1x8,2x4").split(","):
        p, _, d = leg.strip().partition("x")
        sweep.append((int(p), int(d)))

    # the budget the whole sweep prices against: deliberately BETWEEN
    # the 1-host and 2-host per-host peaks, so the single-host verdict
    # is reject:capacity and the 2-host verdict is the "needs 2 hosts"
    # mesh_shards admit — the acceptance scenario, in miniature
    probe = memplane.plan_mesh_shards(total_len, cfg_cpu,
                                      budget_bytes=0, max_hosts=2,
                                      record=False)
    budget = int((probe["single_host_bytes"]
                  + probe["alternatives"]["2"]) // 2)
    predicted = memplane.predict_job_peak_bytes(total_len, cfg_cpu)

    rows, failures = [], 0
    port = args.port
    for rep in range(max(1, args.repeats)):
        for n_procs, n_devs in sweep:
            config = f"p{n_procs}d{n_devs}"
            plan = memplane.plan_mesh_shards(
                total_len, cfg_cpu, budget_bytes=budget,
                max_hosts=n_procs, record=False)
            dec = AdmissionController(
                mem_budget=budget, mesh_hosts=n_procs).admit(
                "bench", predicted_bytes=predicted,
                shard_plan=plan if n_procs > 1 else None)
            admission = (f"admit:mesh_{dec.mesh_shards}"
                         if dec.admitted and dec.mesh_shards
                         else "admit" if dec.admitted
                         else f"reject:{dec.reason}")
            print(f"bench: {config} rep{rep} "
                  f"(admission {admission})...",
                  file=sys.stderr, flush=True)
            t0 = time.perf_counter()
            rcs, outs, timed_out = _spawn_workers(
                n_procs, n_devs, port,
                extra_argv=("--bench", "--oracle-sha", oracle_sha),
                deadline_sec=args.deadline)
            port += 1
            wall_spawn = time.perf_counter() - t0
            reports = {}
            for i, blob in enumerate(outs):
                for line in blob.decode(errors="replace").splitlines():
                    if line.startswith("BENCHJSON "):
                        reports[i] = json.loads(line[len("BENCHJSON "):])
            ok = (not timed_out and not any(rcs)
                  and len(reports) == n_procs
                  and all(r["identical_fasta"]
                          for r in reports.values()))
            if not ok:
                failures += 1
                for i, blob in enumerate(outs):
                    sys.stderr.write(blob.decode(errors="replace"))
            r0 = reports.get(0, {})
            peak = max((r["peak_tracked_bytes"]
                        for r in reports.values()), default=0)
            ratio = (plan["per_host_bytes"] / peak) if peak else 0.0
            in_band = bool(peak) and (1.0 / band) <= ratio <= band
            emit({
                "kind": "row", "series": "MULTICHIP",
                "config": config, "rep": rep,
                "procs": n_procs, "devs": n_devs,
                "shards": int(r0.get("shards", n_procs * n_devs)),
                "hosts": int(r0.get("hosts", 0)),
                "total_len": int(total_len),
                "wall_sec": round(float(r0.get("wall_sec",
                                               wall_spawn)), 4),
                "spawn_sec": round(wall_spawn, 4),
                "identical_fasta": bool(ok),
                "timed_out": bool(timed_out),
                "rcs": rcs,
                "shard_bytes_by_host": {
                    str(i): int(r["shard_bytes"])
                    for i, r in sorted(reports.items())},
                "gather_bytes": int(r0.get("gather_bytes", 0)),
                "h2d_bytes": int(r0.get("h2d_bytes", 0)),
                "d2h_bytes": int(r0.get("d2h_bytes", 0)),
                "budget_bytes": budget,
                "predicted_peak_bytes": int(predicted),
                "per_host_predicted_bytes": plan["per_host_bytes"],
                "mesh_shards_planned": dec.mesh_shards,
                "admission": admission,
                "peak_tracked_bytes": int(peak),
                "capacity_residual": round(ratio, 4),
                "capacity_in_band": bool(in_band),
            })
            rows.append((config, ok, in_band))
    emit({
        "kind": "summary", "series": "MULTICHIP",
        "legs": len(rows), "failures": failures,
        "identical_all": all(ok for _c, ok, _b in rows),
        "capacity_in_band_all": all(b for _c, _ok, b in rows),
        "max_shards": max((p * d for p, d in sweep), default=0),
        "budget_bytes": budget,
        "oracle_sha": oracle_sha,
        "host_cores": os.cpu_count(),
        "ok": failures == 0,
    })
    if out is not sys.stdout:
        out.close()
    return 0 if failures == 0 else 1


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--procs", type=int, default=2)
    ap.add_argument("--devs", type=int, default=4)
    ap.add_argument("--port", type=int, default=9977)
    ap.add_argument("--worker", type=int, default=None)
    ap.add_argument("--bench", action="store_true",
                    help="MULTICHIP JSONL measurement sweep")
    ap.add_argument("--sweep", default="1x8,2x4",
                    help="bench points as PROCSxDEVS, comma-separated")
    ap.add_argument("--repeats", type=int, default=1,
                    help="bench repetitions per point (regression "
                         "series depth)")
    ap.add_argument("--out", default="-",
                    help="bench JSONL sink (- = stdout)")
    ap.add_argument("--deadline", type=float, default=480.0,
                    help="shared per-point worker deadline (seconds)")
    ap.add_argument("--oracle-sha", default="",
                    help="(worker-internal) launcher oracle FASTA hash")
    args = ap.parse_args()

    if args.worker is not None:
        if args.bench:
            rc = bench_worker(args.worker, args.procs, args.devs,
                              args.port, args.oracle_sha)
        else:
            rc = worker(args.worker, args.procs, args.devs, args.port)
        # gloo/distributed client teardown can abort at interpreter
        # exit; the asserts have already decided the outcome
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(rc)

    if args.bench:
        return run_bench(args)

    rcs, outs, timed_out = _spawn_workers(args.procs, args.devs,
                                          args.port)
    sys.stdout.write(outs[0].decode(errors="replace"))
    if timed_out or any(rcs):
        for i in range(1, args.procs):
            sys.stdout.write(outs[i].decode(errors="replace"))
        print(f"MULTIHOST FAILED: rcs={rcs} timed_out={timed_out}")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
