#!/usr/bin/env python3
"""True multi-PROCESS validation of the sharded pipeline (DCN topology).

The single-process dryrun (``__graft_entry__.dryrun_multichip``) proves
the collectives on a virtual mesh inside one controller.  This harness
proves the stronger claim PERF.md §6 makes — "nothing in the code
distinguishes single-host ICI from multi-host DCN" — by actually running
the production ``parallel.dp.ShardedConsensus`` over a mesh that SPANS
OS PROCESSES: ``jax.distributed`` multi-controller, N processes x M
virtual CPU devices each, cross-process collectives over gloo (the CPU
stand-in for DCN).  Each process executes the same SPMD program; the
count tensor's shards live in different address spaces; psum_scatter /
psum run across the process boundary; ``fetch_host`` assembles results
via ``process_allgather``.

Checks (every process asserts, process 0 reports):
  * sharded counts == single-device oracle counts (exact integers);
  * sharded vote symbols == unsharded ``vote_positions``;
  * ``tail_stats`` contig sums == oracle coverage sums.

Usage:
  python tools/multihost_dryrun.py              # spawn 2 procs x 4 devs
  python tools/multihost_dryrun.py --procs 2 --devs 4
  (workers are re-invocations of this script with --worker <pid>)
"""

import argparse
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def worker(pid: int, n_procs: int, n_devs: int, port: int) -> int:
    from sam2consensus_tpu.utils.platform import pin_platform_from_env

    pin_platform_from_env()
    import jax

    jax.distributed.initialize(coordinator_address=f"localhost:{port}",
                               num_processes=n_procs, process_id=pid)
    import numpy as np

    from sam2consensus_tpu.encoder.events import GenomeLayout, ReadEncoder
    from sam2consensus_tpu.io.sam import iter_records, read_header
    from sam2consensus_tpu.ops.cutoff import encode_thresholds
    from sam2consensus_tpu.ops.vote import vote_positions
    from sam2consensus_tpu.parallel.dp import ShardedConsensus
    from sam2consensus_tpu.parallel.mesh import make_mesh
    from sam2consensus_tpu.utils.simulate import SimSpec, simulate
    import io as _io
    import jax.numpy as jnp

    n_global = n_procs * n_devs
    assert len(jax.devices()) == n_global, \
        f"expected {n_global} global devices, got {len(jax.devices())}"
    assert len(jax.local_devices()) == n_devs

    # identical fixture on every process (same seed): multi-controller
    # SPMD requires every process to feed the same global values
    text = simulate(SimSpec(n_contigs=3, contig_len=160, n_reads=400,
                            read_len=24, max_indel=2, seed=77))
    handle = _io.StringIO(text)
    contigs, _n, first = read_header(handle)
    layout = GenomeLayout(contigs)
    enc = ReadEncoder(layout)
    batches = list(enc.encode_segments(iter_records(handle, first), 10 ** 9))

    from sam2consensus_tpu.parallel.dpsp import ProductShardedConsensus
    from sam2consensus_tpu.parallel.sp import PositionShardedConsensus

    mesh = make_mesh(n_global)
    assert mesh.size == n_global

    # oracle: single-device accumulation from the same batches
    want = np.zeros((layout.total_len, 6), dtype=np.int32)
    for b in batches:
        for _w, (starts, codes) in b.buckets.items():
            rows, cols = np.nonzero(codes != 255)
            pos = starts[rows] + cols
            ok = pos < layout.total_len
            np.add.at(want, (pos[ok], codes[rows, cols][ok]), 1)

    thr_enc = encode_thresholds([0.25, 0.75])
    syms1, cov1 = vote_positions(jnp.asarray(want), jnp.asarray(thr_enc), 1)
    want_sums = [np.asarray(cov1)[int(layout.offsets[i]):
                                  int(layout.offsets[i + 1])].sum()
                 for i in range(len(layout.names))]

    # all three production layouts over the process-spanning mesh: dp
    # (scatter + psum_scatter), sp (row routing + ppermute halo), dp x sp
    # (both axes product mode)
    modes = {
        "dp": lambda: ShardedConsensus(mesh, layout.total_len,
                                       pileup="scatter"),
        "sp": lambda: PositionShardedConsensus(mesh, layout.total_len,
                                               halo=64),
        "dpsp": lambda: ProductShardedConsensus(mesh, layout.total_len,
                                                halo=64),
    }
    for mode, build in modes.items():
        sharded = build()
        for b in batches:
            sharded.add(b)
        np.testing.assert_array_equal(sharded.counts_host(), want,
                                      err_msg=f"{mode}: counts diverge")
        syms = sharded.vote(thr_enc, min_depth=1)
        np.testing.assert_array_equal(syms, np.asarray(syms1),
                                      err_msg=f"{mode}: vote diverges")
        contig_sums, _ = sharded.tail_stats(
            layout.offsets.astype(np.int32), np.zeros(0, dtype=np.int32))
        np.testing.assert_array_equal(contig_sums, want_sums,
                                      err_msg=f"{mode}: stats diverge")
        if pid == 0:
            print(f"  [{mode}] counts+vote+stats byte-equal", flush=True)

    if pid == 0:
        print(f"MULTIHOST OK: {n_procs} processes x {n_devs} devices, "
              f"dp/sp/dpsp byte-equal across the process-spanning mesh",
              flush=True)
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--procs", type=int, default=2)
    ap.add_argument("--devs", type=int, default=4)
    ap.add_argument("--port", type=int, default=9977)
    ap.add_argument("--worker", type=int, default=None)
    args = ap.parse_args()

    if args.worker is not None:
        rc = worker(args.worker, args.procs, args.devs, args.port)
        # gloo/distributed client teardown can abort at interpreter
        # exit; the asserts have already decided the outcome
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(rc)

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                        f" --xla_force_host_platform_device_count="
                        f"{args.devs}").strip()
    import signal
    import threading

    # each worker gets its own process group (start_new_session) so a
    # hang can be killed wholesale; one drain thread per pipe so a
    # worker writing a large failure traceback can never block on a
    # full unread pipe while the launcher waits on another worker
    procs = [subprocess.Popen(
        [sys.executable, os.path.abspath(__file__),
         "--worker", str(i), "--procs", str(args.procs),
         "--devs", str(args.devs), "--port", str(args.port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        start_new_session=True)
        for i in range(args.procs)]
    outs = [b""] * args.procs

    def drain(i):
        outs[i] = procs[i].communicate()[0]

    threads = [threading.Thread(target=drain, args=(i,), daemon=True)
               for i in range(args.procs)]
    for t in threads:
        t.start()
    import time

    # one SHARED deadline across all joins (sequential per-thread
    # timeouts would sum to procs x 480 s and outlive the suite test's
    # 560 s outer timeout, leaking killed-launcher worker groups)
    end = time.monotonic() + 480
    for t in threads:
        t.join(timeout=max(0.0, end - time.monotonic()))
    timed_out = any(t.is_alive() for t in threads)
    if timed_out:
        for p in procs:
            if p.poll() is None:
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
        for t in threads:
            t.join(timeout=10)
    rcs = [p.poll() for p in procs]
    sys.stdout.write(outs[0].decode(errors="replace"))
    if timed_out or any(rcs):
        for i in range(1, args.procs):
            sys.stdout.write(outs[i].decode(errors="replace"))
        print(f"MULTIHOST FAILED: rcs={rcs} timed_out={timed_out}")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
