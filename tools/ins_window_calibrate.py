#!/usr/bin/env python3
"""Median-of-3 calibration of the fused insertion-vote auto window.

The ``--insertion-kernel auto`` window (backends.jax_backend
PALLAS_INS_MIN_EVENTS / PALLAS_INS_MAX_EVENTS) was set from SINGLE runs
of the round-5 microbench, and the 1e7-event point flipped 0.77x/2.23x
between two runs — tunnel-state variance, not a property of the kernel
(VERDICT r5 #4).  This tool re-measures the decision-relevant
comparison — scatter table + XLA vote vs the fused in-kernel vote — at
each event scale as the MEDIAN OF N INDEPENDENT RUNS (default 3,
MB_CAL_RUNS), emitting every per-run sample alongside the median so the
variance itself is in the artifact.  The campaign step commits
``campaign/ins_window_<round>.jsonl``; a window re-pin cites those rows.

Decision rule applied to the medians: the auto window keeps the fused
kernel wherever ``median(scatter_tail / fused_tail) >= FUSED_MIN_WIN``
(default 1.15 — a kernel that wins by less than tunnel-RT noise should
not preempt the scatter path).

Run on real hardware:  python tools/ins_window_calibrate.py
CI / no accelerator:   JAX_PLATFORMS=cpu IW_POINTS=tiny python tools/ins_window_calibrate.py
Knobs: IW_POINTS (full|tiny), IW_REPEATS (default 5), MB_CAL_RUNS (3),
FUSED_MIN_WIN (1.15).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sam2consensus_tpu.utils.platform import pin_platform_from_env  # noqa: E402
pin_platform_from_env()

import numpy as np  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def emit(**kw):
    print(json.dumps(kw), flush=True)


def timed(fn, repeats):
    ts = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = fn()
        leaf = jax.tree_util.tree_leaves(out)[0]
        np.asarray(leaf.ravel()[0])
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def measure_point(n_sites, n_events, repeats, interp):
    """One (scatter_tail_sec, fused_tail_sec) sample."""
    from sam2consensus_tpu.ops import pallas_insertion
    from sam2consensus_tpu.ops.cutoff import encode_thresholds
    from sam2consensus_tpu.ops.insertions import (build_insertion_table,
                                                  vote_insertions)

    rng = np.random.default_rng(11)
    max_cols = 8
    ev_key = np.sort(rng.integers(0, n_sites, n_events)).astype(np.int32)
    ev_col = rng.integers(0, max_cols, n_events).astype(np.int32)
    ev_code = rng.integers(0, 6, n_events).astype(np.int32)
    kp = 1 << max(1, (n_sites + 1 - 1).bit_length())
    cp = 1 << max(1, (max_cols - 1).bit_length())
    site_cov = rng.integers(0, 200, kp).astype(np.int32)
    n_cols = np.full(kp, max_cols, dtype=np.int32)
    thr = encode_thresholds([0.25])

    def run_scatter_tail():
        table = jnp.zeros((kp, cp, 6), dtype=jnp.int32)
        table = build_insertion_table(table, jnp.asarray(ev_key),
                                      jnp.asarray(ev_col),
                                      jnp.asarray(ev_code))
        return vote_insertions(table, jnp.asarray(site_cov),
                               jnp.asarray(n_cols), jnp.asarray(thr))

    eplan = pallas_insertion.plan_events(ev_key, ev_col, ev_code,
                                         n_sites, cp)
    kmin = min(kp, eplan.kp)
    sc_p = np.zeros(eplan.kp, np.int32)
    sc_p[:kmin] = site_cov[:kmin]
    nc_p = np.zeros(eplan.kp, np.int32)
    nc_p[:kmin] = n_cols[:kmin]

    def run_fused_tail():
        return pallas_insertion.vote_insertions_pallas(
            eplan, sc_p, nc_p, thr, cp, interpret=interp)

    _ = run_scatter_tail()             # warm compiles outside timing
    _ = run_fused_tail()
    return (timed(run_scatter_tail, repeats),
            timed(run_fused_tail, repeats))


def main():
    platform = jax.default_backend()
    interp = platform != "tpu"
    repeats = int(os.environ.get("IW_REPEATS", "5"))
    runs = int(os.environ.get("MB_CAL_RUNS", "3"))
    min_win = float(os.environ.get("FUSED_MIN_WIN", "1.15"))
    tiny = os.environ.get("IW_POINTS", "full") == "tiny" or interp
    emit(op="env", platform=platform, interpret=interp, repeats=repeats,
         runs=runs, fused_min_win=min_win,
         note=("interpret-mode ratios are NOT chip evidence; rerun on "
               "the TPU rig before re-pinning the window"
               if interp else "median-of-%d calibration" % runs))
    if tiny:
        points = [(500, 20_000), (2_000, 100_000)]
    else:
        points = [(500, 20_000), (5_000, 200_000),
                  (20_000, 2_000_000), (50_000, 8_000_000),
                  (100_000, 10_000_000)]
    window = []
    for sites, events in points:
        samples = [measure_point(sites, events, repeats, interp)
                   for _ in range(runs)]
        ratios = [s / f for s, f in samples]
        med = float(np.median(ratios))
        spread = float(max(ratios) - min(ratios))
        fused_wins = med >= min_win
        if fused_wins:
            window.append(events)
        emit(op="ins_window", sites=sites, events=events,
             scatter_sec=[round(s, 5) for s, _f in samples],
             fused_sec=[round(f, 5) for _s, f in samples],
             ratio_runs=[round(r, 3) for r in ratios],
             ratio_median=round(med, 3), ratio_spread=round(spread, 3),
             fused_wins=bool(fused_wins))
    emit(op="ins_window_summary",
         fused_window_events=[min(window), max(window)] if window
         else None,
         rule=f"fused wins where median ratio >= {min_win}")


if __name__ == "__main__":
    main()
