#!/usr/bin/env python3
"""Incremental-consensus benchmark (the ISSUE-13 tentpole's evidence).

Measures what a tenant pays to add +N% reads against a reference whose
count state is already warm in the serve count cache
(``--count-cache``; serve/countcache.py) vs the cold job over the
combined input — both through ONE warm ServeRunner, outputs
byte-compared before anything is timed, min-of-N alternating passes
(each warm pass restores the cache entry to its post-base state so the
duplicate-input no-op can't flatter the number).  Writes per-pass rows
plus a summary row as JSONL (``--out``; stdout otherwise).  The
summary's ``incr_cost_ratio`` (target <= 0.15) and ``identical`` are
the acceptance numbers; ``cache`` (hit/evict counters) and
``decision`` (the count_cache ledger record with its residual) are
the why.

Campaign usage (tools/tpu_campaign.sh step ``incremental``) tags the
artifact per round; the CPU-fallback harness proof lives at
campaign/incremental_r06_cpufallback.jsonl.

Usage: python tools/incremental_bench.py [--reads 1000000]
       [--extra-pct 10] [--contig-len 50000] [--read-len 100]
       [--passes 3] [--cache 256M] [--out FILE.jsonl]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--reads", type=int, default=1_000_000,
                    help="base read count the reference absorbs first")
    ap.add_argument("--extra-pct", type=int, default=10)
    ap.add_argument("--contig-len", type=int, default=50_000)
    ap.add_argument("--read-len", type=int, default=100)
    ap.add_argument("--passes", type=int, default=3)
    ap.add_argument("--cache", default="256M",
                    help="count-cache byte budget")
    ap.add_argument("--out", default=None,
                    help="JSONL destination (default: stdout)")
    args = ap.parse_args(argv)

    from sam2consensus_tpu.utils.platform import pin_platform_from_env

    pin_platform_from_env()

    from sam2consensus_tpu.serve.benchmark import run_incremental_bench

    res = run_incremental_bench(
        n_reads=args.reads, extra_pct=args.extra_pct,
        contig_len=args.contig_len, read_len=args.read_len,
        passes=args.passes, cache_budget=args.cache, log=log)
    lines = [json.dumps(r) for r in res["rows"]]
    lines.append(json.dumps(res["summary"]))
    blob = "\n".join(lines) + "\n"
    if args.out and args.out != "-":
        with open(args.out, "w") as fh:
            fh.write(blob)
        log(f"[incremental] wrote {args.out}")
    else:
        sys.stdout.write(blob)
    s = res["summary"]
    return 0 if (s["identical"]
                 and s["incr_cost_ratio"] <= s["target_ratio"]) else 1


if __name__ == "__main__":
    sys.exit(main())
