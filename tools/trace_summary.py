#!/usr/bin/env python3
"""One-shot trace triage: where did the time actually go?

Usage: python tools/trace_summary.py <trace.json> [trace2.json ...]
                                     [-n TOP] [--inclusive | --flame]

Reads ``ph: "X"`` complete events from a Chrome/Perfetto trace-event
JSON (the CLI's ``--trace-out`` artifact) and prints the top-N span
NAMES by aggregate EXCLUSIVE self-time — each span's duration minus its
direct children's (nesting is timestamp containment within a thread,
exactly how Perfetto renders ``ph: X``).  Without the self-time
subtraction a nested tree double-bills every parent phase: the
``accumulate`` window CONTAINS every ``pileup_dispatch`` and ``slab``
span, so the old inclusive top-N said "accumulate is 100%, dispatch is
90%, slabs are 85%" of the same second.  ``--inclusive`` restores the
raw widest-single-span ranking for when that's the question.

``--flame`` emits collapsed-stack lines (``root;child;leaf N`` — N in
integer microseconds of EXCLUSIVE self-time, from the same stack
pass), the input format of Brendan Gregg's ``flamegraph.pl`` and of
speedscope's "collapsed stacks" importer:

    python tools/trace_summary.py trace.json --flame > out.collapsed
    flamegraph.pl out.collapsed > flame.svg

Multiple traces (or a quoted glob — ``'run/trace_*.json'`` is expanded
here for shells that don't) merge into ONE ranking, so an N-worker
fleet run (ISSUE 16's per-worker ``--trace-out`` artifacts) needs one
invocation, not N.  In merged mode each file's spans are kept on their
own thread keys (two workers' tid 0 must not nest into each other) and
``--flame`` paths gain a ``<worker>;`` stack root — the worker id from
the trace's ``s2c`` metadata block when stamped, else the file's
basename — so a fleet flamegraph splits per worker at the base.
"""

import argparse
import glob
import json
import os
import sys
from collections import defaultdict


def load_events(path):
    with open(path) as fh:
        obj = json.load(fh)
    events = obj["traceEvents"] if isinstance(obj, dict) else obj
    return [e for e in events if e.get("ph") == "X"]


def load_trace(path):
    """(complete-spans, worker-label) for one trace file; the label is
    the ``s2c`` metadata block's worker id when the serve runner
    stamped one, else the file basename."""
    with open(path) as fh:
        obj = json.load(fh)
    events = obj["traceEvents"] if isinstance(obj, dict) else obj
    meta = obj.get("s2c") or {} if isinstance(obj, dict) else {}
    worker = str(meta.get("worker") or "") \
        or os.path.splitext(os.path.basename(path))[0]
    return [e for e in events if e.get("ph") == "X"], worker


def load_merged(paths):
    """Spans from N trace files on disjoint thread keys (file index
    paired into the tid), each tagged with its worker label."""
    spans = []
    for fi, path in enumerate(paths):
        s, worker = load_trace(path)
        for e in s:
            e["tid"] = (fi, e.get("tid", 0))
            e["_worker"] = worker
        spans.extend(s)
    return spans


def _stack_pass(spans):
    """THE nesting reconstruction, shared by :func:`self_times` and
    :func:`collapsed_stacks` so the two can never diverge: one stack
    pass per thread over (ts, -dur)-sorted spans — when the next span
    starts after the stack top ends, the top is closed; a span
    starting inside the top is its direct child and bills its whole
    duration to exactly that parent (grandparents already billed the
    child's parent, so nothing double-subtracts).  Ties sort the
    longer span first, so a child sharing its parent's start timestamp
    nests under it instead of beside it.

    Returns ``[(ancestor_path, event, child_dur_acc)]`` in per-thread
    scan order; exclusive self-time is ``max(0, dur - acc[0])`` once
    the pass completes.
    """
    by_tid = defaultdict(list)
    for e in spans:
        by_tid[e.get("tid", 0)].append(e)
    records = []
    for tid_spans in by_tid.values():
        tid_spans.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack = []      # [(end_ts, name, child_dur_accum_list)]
        for e in tid_spans:
            end = e["ts"] + e["dur"]
            while stack and e["ts"] >= stack[-1][0] - 1e-9:
                stack.pop()
            if stack:
                stack[-1][2][0] += e["dur"]
            acc = [0.0]
            path = ";".join([s[1] for s in stack] + [e["name"]])
            stack.append((end, e["name"], acc))
            records.append((path, e, acc))
    return records


def self_times(spans):
    """Per-span exclusive duration: ``dur`` minus the summed ``dur`` of
    DIRECT children (same tid, timestamp-contained).  Returns a list of
    (event, self_us)."""
    return [(e, max(0.0, e["dur"] - acc[0]))
            for _path, e, acc in _stack_pass(spans)]


def collapsed_stacks(spans):
    """Per-stack-path exclusive self-time: ``{"a;b;c": self_us}``.

    Literally :func:`self_times`'s shared stack pass
    (:func:`_stack_pass`) with the ancestor name chain kept — a leaf's
    self-time bills to the full path, which is exactly what a
    flamegraph renders.  Paths from different threads merge by name
    chain (the per-phase story an operator wants; pass one tid's spans
    to keep threads apart)."""
    agg = defaultdict(float)
    for path, e, acc in _stack_pass(spans):
        agg[path] += max(0.0, e["dur"] - acc[0])
    return dict(agg)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("trace", nargs="+",
                   help="trace-event JSON file(s) or glob(s) "
                        "(--trace-out output); several merge into one "
                        "ranking")
    p.add_argument("-n", "--top", type=int, default=5,
                   help="rows to print (default 5)")
    p.add_argument("--inclusive", action="store_true",
                   help="rank individual spans by raw (inclusive) "
                        "duration instead of aggregating self-time")
    p.add_argument("--flame", action="store_true",
                   help="emit collapsed-stack lines (path;to;span N, "
                        "N = exclusive self-microseconds) for "
                        "flamegraph.pl / speedscope instead of the "
                        "top-N table")
    args = p.parse_args(argv)

    paths = []
    for pat in args.trace:
        hits = sorted(glob.glob(pat))
        paths.extend(hits if hits else [pat])
    merged = len(paths) > 1
    spans = load_merged(paths) if merged else load_events(paths[0])
    if args.flame:
        if not spans:
            print("no complete spans in trace", file=sys.stderr)
            return 1
        if merged:
            # worker; stack root: a fleet flamegraph splits per
            # worker at the base instead of smearing N workers'
            # same-named phases into one frame
            agg = defaultdict(float)
            for spath, e, acc in _stack_pass(spans):
                agg[f"{e['_worker']};{spath}"] += \
                    max(0.0, e["dur"] - acc[0])
            stacks = dict(agg)
        else:
            stacks = collapsed_stacks(spans)
        for path, self_us in sorted(stacks.items()):
            n = int(round(self_us))
            if n > 0:
                print(f"{path} {n}")
        return 0
    if not spans:
        print("no complete spans in trace", file=sys.stderr)
        return 1
    wall_us = max(e["ts"] + e["dur"] for e in spans) \
        - min(e["ts"] for e in spans)

    if args.inclusive:
        spans.sort(key=lambda e: e["dur"], reverse=True)
        print(f"{len(spans)} spans, wall {wall_us / 1e6:.4f}s — "
              f"top {min(args.top, len(spans))} by inclusive duration:")
        print(f"{'span':<24} {'dur_s':>10} {'% wall':>7}  args")
        for e in spans[:args.top]:
            arg_txt = ""
            if e.get("args"):
                arg_txt = " ".join(f"{k}={v}"
                                   for k, v in e["args"].items())
            pct = 100.0 * e["dur"] / wall_us if wall_us > 0 else 0.0
            print(f"{e['name']:<24} {e['dur'] / 1e6:>10.4f} "
                  f"{pct:>6.1f}%  {arg_txt}")
        return 0

    agg = defaultdict(lambda: [0, 0.0, 0.0])   # name -> [n, self, incl]
    for e, self_us in self_times(spans):
        a = agg[e["name"]]
        a[0] += 1
        a[1] += self_us
        a[2] += e["dur"]
    rows = sorted(agg.items(), key=lambda kv: kv[1][1], reverse=True)
    total_self = sum(a[1] for _n, a in rows)
    print(f"{len(spans)} spans / {len(rows)} names, "
          f"wall {wall_us / 1e6:.4f}s — "
          f"top {min(args.top, len(rows))} by exclusive self-time:")
    print(f"{'span':<24} {'count':>6} {'self_s':>10} {'% self':>7} "
          f"{'incl_s':>10}")
    for name, (n, self_us, incl_us) in rows[:args.top]:
        pct = 100.0 * self_us / total_self if total_self > 0 else 0.0
        print(f"{name:<24} {n:>6} {self_us / 1e6:>10.4f} {pct:>6.1f}% "
              f"{incl_us / 1e6:>10.4f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
