#!/usr/bin/env python3
"""One-shot trace triage: print the top-N widest spans from a Chrome/
Perfetto trace-event JSON (the CLI's ``--trace-out`` artifact).

Usage: python tools/trace_summary.py <trace.json> [-n TOP]

Reads ``ph: "X"`` complete events, ranks by ``dur``, and prints one
line per span with its share of the trace's wall clock — the first
question every perf investigation asks ("where did the time go?")
answered without opening a UI.
"""

import argparse
import json
import sys


def load_events(path):
    with open(path) as fh:
        obj = json.load(fh)
    events = obj["traceEvents"] if isinstance(obj, dict) else obj
    return [e for e in events if e.get("ph") == "X"]


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("trace", help="trace-event JSON (--trace-out output)")
    p.add_argument("-n", "--top", type=int, default=5,
                   help="spans to print (default 5)")
    args = p.parse_args(argv)

    spans = load_events(args.trace)
    if not spans:
        print("no complete spans in trace", file=sys.stderr)
        return 1
    wall_us = max(e["ts"] + e["dur"] for e in spans) \
        - min(e["ts"] for e in spans)
    spans.sort(key=lambda e: e["dur"], reverse=True)
    print(f"{len(spans)} spans, wall {wall_us / 1e6:.4f}s — "
          f"top {min(args.top, len(spans))} by duration:")
    print(f"{'span':<24} {'dur_s':>10} {'% wall':>7}  args")
    for e in spans[:args.top]:
        arg_txt = ""
        if e.get("args"):
            arg_txt = " ".join(f"{k}={v}" for k, v in e["args"].items())
        pct = 100.0 * e["dur"] / wall_us if wall_us > 0 else 0.0
        print(f"{e['name']:<24} {e['dur'] / 1e6:>10.4f} {pct:>6.1f}%  "
              f"{arg_txt}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
