#!/usr/bin/env python3
"""Chaos soak: kill/hang/fault cycles against a journaled serve queue.

The serve survivability acceptance harness (ISSUE r6): N cycles, each
running a multi-job ``s2c serve --journal`` queue under one chaos mode —

* ``kill``        — SIGKILL the server after its first commit, restart
                    the same command, let the journal resume the queue;
* ``hang``        — every job's first device dispatch wedges
                    (``job_hang`` fault site + S2C_FAULT_HANG_S); the
                    watchdog (--job-timeout) abandons it and the job
                    retries on the ladder's host rung (fallback mode);
* ``fault``       — persistent injected RPC faults on every pileup
                    dispatch; the in-run ladder demotes each job to the
                    host rung mid-flight;
* ``kill_fault``  — the fault mode PLUS a ``journal_write`` fault on
                    the first journal append (durability degraded, not
                    correctness) PLUS a mid-queue SIGKILL + restart.
                    (``serve_decode_ahead`` cannot fire here — journal
                    mode runs serial decode — it is exercised by
                    tests/test_survivability.py instead.)

After the cycles, one **ingest_demote** leg runs (always): journaled
jobs checkpoint and therefore keep the serial decode rung, so the
byte-shard scheduler's ``ingest_decode_shard`` site gets a one-shot-CLI
cycle of its own — a PERSISTENT shard fault under ``--decode-threads
2`` must demote the whole ingest to the serial rung
(``ingest/demoted``) with output byte-identical to a clean run.

Every cycle asserts the three survivability invariants:

1. **byte identity** — the cycle's output set is sha256-identical to a
   chaos-free baseline run of the same queue;
2. **zero lost / zero duplicated jobs** — the journal's fingerprint
   audit (serve/journal.py ``audit()``): every submitted key committed
   exactly once across the cycle's whole journal;
3. **bounded recovery** — the recovery phase (the restarted process for
   kill modes, the whole chaos-laden process otherwise) completes
   within ``--max-recovery-sec``.

One JSON row per cycle + a summary row, as JSONL on stdout (or
``--out``); ``recovery_sec`` rides the noise-aware regression gate
(``tools/regress_check.py --jsonl campaign/chaos_soak_<r>.jsonl
--group-by mode --value recovery_sec``).  Campaign step ``chaos_soak``
(tools/tpu_campaign.sh); the CPU-fallback harness proof is committed at
campaign/chaos_soak_r06_cpufallback.jsonl.

Usage: python tools/chaos_soak.py [--cycles 8] [--jobs 3]
       [--reads 20000] [--contig-len 6000] [--max-recovery-sec 180]
       [--out FILE.jsonl]
"""

import argparse
import hashlib
import json
import os
import shutil
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

MODES = ("kill", "hang", "fault", "kill_fault")


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def sha_dir(d):
    out = {}
    for name in sorted(os.listdir(d)):
        p = os.path.join(d, name)
        h = hashlib.sha256()
        with open(p, "rb") as fh:
            h.update(fh.read())
        out[name] = h.hexdigest()
    return out


def serve_cmd(inputs, outdir, jdir, extra=()):
    cmd = [sys.executable, "-m", "sam2consensus_tpu.cli", "serve"]
    for p in inputs:
        cmd += ["-i", p]
    cmd += ["-o", outdir, "--journal", jdir, "--pileup", "scatter",
            "--quiet", *extra]
    return cmd


def committed_count(jdir):
    n = 0
    try:
        names = os.listdir(jdir)
    except OSError:
        return 0
    for name in names:
        if not (name.startswith("ev-") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(jdir, name)) as fh:
                if json.load(fh).get("ev") == "committed":
                    n += 1
        except Exception:
            continue
    return n


def run_to_completion(cmd, env, timeout):
    t0 = time.monotonic()
    r = subprocess.run(cmd, env=env, capture_output=True, text=True,
                       timeout=timeout)
    return r.returncode, time.monotonic() - t0, r


def kill_after_first_commit(cmd, env, jdir, n_jobs, timeout):
    """Launch the server and SIGKILL it once >=1 job committed (but
    before the whole queue did).  Returns ('killed'|'finished', rc)."""
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            return "finished", proc.returncode
        n = committed_count(jdir)
        if 1 <= n < n_jobs:
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=30)
            return "killed", -9
        time.sleep(0.05)
    proc.kill()
    proc.wait(timeout=30)
    return "timeout", -9


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--cycles", type=int, default=8)
    ap.add_argument("--jobs", type=int, default=3)
    ap.add_argument("--reads", type=int, default=20000)
    ap.add_argument("--contig-len", type=int, default=6000)
    ap.add_argument("--read-len", type=int, default=100)
    ap.add_argument("--job-timeout", type=float, default=4.0,
                    help="watchdog deadline for the hang cycles")
    ap.add_argument("--max-recovery-sec", type=float, default=180.0)
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: a fresh tempdir)")
    ap.add_argument("--per-process-timeout", type=float, default=600.0)
    ap.add_argument("--out", default=None,
                    help="JSONL destination (default: stdout)")
    args = ap.parse_args(argv)

    import tempfile

    from sam2consensus_tpu.serve.journal import JobJournal
    from sam2consensus_tpu.utils.simulate import SimSpec, simulate

    work = args.workdir or tempfile.mkdtemp(prefix="s2c_chaos_")
    os.makedirs(work, exist_ok=True)
    log(f"[chaos_soak] workdir {work}")

    inputs = []
    for k in range(args.jobs):
        spec = SimSpec(n_contigs=1, contig_len=args.contig_len,
                       n_reads=args.reads, read_len=args.read_len,
                       contig_len_jitter=0.0, seed=4200 + k,
                       contig_prefix=f"cs{k:02d}_")
        p = os.path.join(work, f"job{k}.sam")
        with open(p, "w") as fh:
            fh.write(simulate(spec))
        inputs.append(p)

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # one persistent compile cache for the whole soak: restarts are
    # measuring RECOVERY, not XLA re-compilation
    env["S2C_JIT_CACHE"] = os.path.join(work, "_jit_cache")

    # chaos-free baseline: the byte-identity oracle for every cycle
    base_out = os.path.join(work, "out_base")
    rc, base_sec, r = run_to_completion(
        serve_cmd(inputs, base_out, os.path.join(work, "j_base")), env,
        args.per_process_timeout)
    if rc != 0:
        log(f"[chaos_soak] baseline failed rc={rc}:\n{r.stderr[-2000:]}")
        return 2
    want = sha_dir(base_out)
    log(f"[chaos_soak] baseline {base_sec:.1f}s, "
        f"{len(want)} output file(s)")

    rows = []
    failures = 0
    for c in range(args.cycles):
        mode = MODES[c % len(MODES)]
        outdir = os.path.join(work, f"out_c{c}")
        jdir = os.path.join(work, f"j_c{c}")
        for d in (outdir, jdir):
            shutil.rmtree(d, ignore_errors=True)
        cyc_env = dict(env)
        extra = []
        if mode in ("hang",):
            # every job's first dispatch wedges; the watchdog abandons
            # it and fallback mode re-runs the job on the host rung
            extra += ["--fault-inject", "job_hang:timeout:0:1",
                      "--on-device-error", "fallback",
                      "--job-timeout", str(args.job_timeout)]
            cyc_env["S2C_FAULT_HANG_S"] = "900"
        elif mode in ("fault", "kill_fault"):
            spec = "pileup_dispatch:rpc:0:inf"
            if mode == "kill_fault":
                # the runner-scope journal_write site too: the first
                # journal append of each process fails (absorbed —
                # durability degraded, correctness intact; the restart
                # + fingerprint audit below prove it)
                spec += ",journal_write:rpc:0:1"
            extra += ["--fault-inject", spec,
                      "--on-device-error", "fallback",
                      "--retries", "1", "--retry-backoff", "0.01"]
        cmd = serve_cmd(inputs, outdir, jdir, extra)
        t_cycle = time.monotonic()
        killed = False
        recovery_sec = None
        rc = 0
        if mode in ("kill", "kill_fault"):
            verdict, _rc = kill_after_first_commit(
                cmd, cyc_env, jdir, args.jobs,
                args.per_process_timeout)
            killed = verdict == "killed"
            if verdict == "timeout":
                rc = -1
            # the recovery phase: the restarted server drains the
            # journaled queue (skips committed, resumes in-flight)
            rc2, recovery_sec, r2 = run_to_completion(
                cmd, cyc_env, args.per_process_timeout)
            rc = rc or rc2
            if rc2 != 0:
                log(f"[chaos_soak] c{c} restart rc={rc2}: "
                    f"{r2.stderr[-1500:]}")
        else:
            rc, recovery_sec, r1 = run_to_completion(
                cmd, cyc_env, args.per_process_timeout)
            if rc != 0:
                log(f"[chaos_soak] c{c} rc={rc}: {r1.stderr[-1500:]}")
        total_sec = time.monotonic() - t_cycle

        got = sha_dir(outdir) if os.path.isdir(outdir) else {}
        identical = got == want
        audit = JobJournal(jdir).audit()
        lost, dup = audit["lost"], audit["duplicated"]
        ok = (rc == 0 and identical and not lost and not dup
              and recovery_sec <= args.max_recovery_sec)
        failures += 0 if ok else 1
        row = {"cycle": c, "mode": mode, "ok": ok, "rc": rc,
               "killed": killed,
               "recovery_sec": round(recovery_sec, 3),
               "total_sec": round(total_sec, 3),
               "jobs": args.jobs, "identical": identical,
               "lost": len(lost), "duplicated": len(dup),
               "committed": len(audit["commit_counts"])}
        rows.append(row)
        log(f"[chaos_soak] c{c} {mode}: "
            + ("OK" if ok else "FAIL")
            + f" recovery {recovery_sec:.1f}s"
            + (" (killed mid-queue)" if killed else ""))

    # Dedicated ingest-demotion leg: journaled jobs checkpoint, and
    # checkpointed runs keep the SERIAL decode rung — so the byte-shard
    # scheduler's fault site (ingest_decode_shard) gets its own
    # one-shot-CLI soak cycle: with a PERSISTENT shard fault every
    # shard fails its retry, the whole ingest must demote to the serial
    # rung, and the output must still be byte-identical to a clean run
    # (the merge-never-corrupted contract of
    # encoder/parallel_decode.py).
    def oneshot(outdir, extra):
        os.makedirs(outdir, exist_ok=True)
        return [sys.executable, "-m", "sam2consensus_tpu.cli",
                "-i", inputs[0], "-o", outdir, *extra]

    ing_clean = os.path.join(work, "ing_clean")
    ing_out = os.path.join(work, "ing_out")
    ing_metrics = os.path.join(work, "ing_metrics.json")
    t_cycle = time.monotonic()
    rc1, _t, r1 = run_to_completion(
        oneshot(ing_clean, ["--decode-threads", "2"]), env,
        args.per_process_timeout)
    rc2, ing_sec, r2 = run_to_completion(
        oneshot(ing_out, ["--decode-threads", "2",
                          "--fault-inject",
                          "ingest_decode_shard:rpc:0:inf",
                          "--json-metrics", ing_metrics]), env,
        args.per_process_timeout)
    ing_identical = (rc1 == 0 and rc2 == 0
                     and sha_dir(ing_clean) == sha_dir(ing_out))
    try:
        with open(ing_metrics) as fh:
            m = json.load(fh)
        demoted = int(m.get("ingest/demoted", 0))
        retries = int(m.get("ingest/shard_retries", 0))
    except Exception:
        demoted = retries = 0
    ing_ok = ing_identical and demoted >= 1 and retries >= 1
    failures += 0 if ing_ok else 1
    if not ing_ok:
        log(f"[chaos_soak] ingest_demote rc1={rc1} rc2={rc2}: "
            f"{(r2.stderr or r1.stderr)[-1500:]}")
    rows.append({"cycle": "ingest", "mode": "ingest_demote",
                 "ok": ing_ok, "rc": rc1 or rc2,
                 "killed": False, "identical": ing_identical,
                 "demoted": demoted, "shard_retries": retries,
                 "recovery_sec": round(ing_sec, 3),
                 "total_sec": round(time.monotonic() - t_cycle, 3),
                 "jobs": 1, "lost": 0, "duplicated": 0, "committed": 0})
    log(f"[chaos_soak] ingest_demote: "
        + ("OK" if ing_ok else "FAIL")
        + f" demoted={demoted} retries={retries}")

    rec = [r["recovery_sec"] for r in rows]
    summary = {
        "mode": "summary",
        "cycles": args.cycles, "jobs": args.jobs,
        "reads": args.reads, "contig_len": args.contig_len,
        "identical_all": all(r["identical"] for r in rows),
        "lost_total": sum(r["lost"] for r in rows),
        "duplicated_total": sum(r["duplicated"] for r in rows),
        "killed_cycles": sum(1 for r in rows if r["killed"]),
        "max_recovery_sec": round(max(rec), 3),
        "median_recovery_sec": round(sorted(rec)[len(rec) // 2], 3),
        "baseline_sec": round(base_sec, 3),
        "max_recovery_bound_sec": args.max_recovery_sec,
        "failures": failures,
        "platform": os.environ.get("JAX_PLATFORMS", ""),
    }
    lines = [json.dumps(r) for r in rows] + [json.dumps(summary)]
    blob = "\n".join(lines) + "\n"
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(blob)
        log(f"[chaos_soak] wrote {args.out}")
    else:
        sys.stdout.write(blob)
    if not args.workdir:
        shutil.rmtree(work, ignore_errors=True)
    return 0 if failures == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
