#!/usr/bin/env python3
"""Data-resident Pallas tile-CSR pileup sweep: the 735 Mcells/s artifact.

PERF.md R5.2 quotes the Pallas tile-CSR kernel at 735 Mcells/s
data-resident (8.8x the resident scatter) but the round-5 campaign never
committed the sweep itself (VERDICT r5 #2) — the microbench artifact
only carries the END-TO-END rows (host plan + transfer + kernel), which
the tunnel dominates.  This tool measures the DATA-RESIDENT rates: every
operand (starts, packed codes, CSR plan) is device_put once, then each
implementation is re-dispatched over the resident operands and timed
with a one-element fetch per repeat.  Each (rows, width, genome) point
reports the MEDIAN OF N INDEPENDENT RUNS (default 3, MB_CAL_RUNS) so a
single noisy tunnel window cannot set a constant (VERDICT r5 #4 applied
to this sweep too).

One JSON object per line; the campaign step commits
``campaign/pallas_sweep_<round>.jsonl``.

Run on real hardware:  python tools/pallas_sweep.py
CI / no accelerator:   JAX_PLATFORMS=cpu PS_POINTS=tiny python tools/pallas_sweep.py
Knobs: PS_POINTS (full|tiny), PS_REPEATS (per-run repeats, default 5),
MB_CAL_RUNS (outer runs per point, default 3).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sam2consensus_tpu.utils.platform import pin_platform_from_env  # noqa: E402
pin_platform_from_env()

import numpy as np  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def emit(**kw):
    print(json.dumps(kw), flush=True)


def fetch_one(out):
    leaf = jax.tree_util.tree_leaves(out)[0]
    np.asarray(leaf.ravel()[0])


def timed_resident(fn, repeats):
    """Median seconds per dispatch over resident operands."""
    ts = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fetch_one(fn())
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def sweep_point(rows, width, genome_len, repeats, runs, interpret):
    from sam2consensus_tpu.constants import NUM_SYMBOLS
    from sam2consensus_tpu.ops import pallas_pileup as pp
    from sam2consensus_tpu.ops.pileup import (_scatter_segments_packed,
                                              pack_nibbles)

    rng = np.random.default_rng(7)
    tile = pp.TILE_POSITIONS
    padded_len = -(-(genome_len + 1) // tile) * tile
    starts = np.sort(rng.integers(0, genome_len - width, rows)) \
        .astype(np.int32)
    codes = rng.integers(0, 6, (rows, width)).astype(np.uint8)
    codes[rng.random(codes.shape) < 0.05] = 255
    cells = rows * width

    packed = pack_nibbles(codes)
    s_dev = jax.device_put(starts)
    p_dev = jax.device_put(packed)
    plan = pp.plan_rows(starts.astype(np.int64), width, padded_len, tile)
    rank_dev = jax.device_put(plan.rank)
    lo_dev = jax.device_put(plan.blk_lo)
    n_dev = jax.device_put(plan.blk_n)

    def run_scatter():
        return _scatter_segments_packed(
            jnp.zeros((padded_len, NUM_SYMBOLS), jnp.int32),
            s_dev, p_dev, genome_len)

    def run_pallas():
        return pp.pileup_pallas_packed(
            jnp.zeros((padded_len, NUM_SYMBOLS), jnp.int32),
            s_dev, p_dev, rank_dev, tile=tile, n_tiles=plan.n_tiles,
            width=width, row_block=plan.row_block,
            max_blocks=plan.max_blocks,
            n_rows_padded=plan.n_rows_padded,
            blk_lo=lo_dev, blk_n=n_dev, interpret=interpret)

    fetch_one(run_scatter())              # warm compiles outside timing
    fetch_one(run_pallas())

    point = {"rows": rows, "width": width, "genome_len": genome_len,
             "cells": cells, "interpret": interpret}
    results = {}
    for impl, fn in (("scatter", run_scatter), ("pallas_csr", run_pallas)):
        per_run = [timed_resident(fn, repeats) for _ in range(runs)]
        sec = float(np.median(per_run))
        results[impl] = sec
        emit(op="pallas_sweep", impl=impl, **point, sec=round(sec, 5),
             runs=[round(t, 5) for t in per_run],
             mcells_per_s=round(cells / sec / 1e6, 1))
    emit(op="pallas_sweep_point", **point,
         pallas_speedup_vs_scatter=round(
             results["scatter"] / results["pallas_csr"], 2))


def main():
    platform = jax.default_backend()
    interpret = platform != "tpu"
    repeats = int(os.environ.get("PS_REPEATS", "5"))
    runs = int(os.environ.get("MB_CAL_RUNS", "3"))
    tiny = os.environ.get("PS_POINTS", "full") == "tiny" or interpret
    emit(op="env", platform=platform,
         device_kind=getattr(jax.devices()[0], "device_kind", platform),
         interpret=interpret, repeats=repeats, runs=runs,
         note=("interpret-mode rates are NOT chip evidence; rerun on "
               "the TPU rig for the data-resident claim"
               if interpret else "data-resident (operands device_put "
               "once, kernel re-dispatched)"))
    if tiny:
        points = [(4096, 128, 1 << 18)]
    else:
        # the R5.2 claim's shape first (65536x128 over the ecoli-scale
        # genome), then the density/width axes around it
        points = [(65536, 128, 4_600_000),
                  (16384, 128, 4_600_000),
                  (65536, 256, 4_600_000),
                  (65536, 128, 40_000_000)]
    for rows, width, genome_len in points:
        sweep_point(rows, width, genome_len, repeats, runs, interpret)


if __name__ == "__main__":
    main()
