#!/usr/bin/env python3
"""Continuous-batching serve benchmark (the PR-11 tentpole's evidence).

Runs one small-job queue through a warm ServeRunner two ways — strictly
serial (``--batch off``, the pre-batching warm path) and packed
(``--batch N``: shared slabs, one shared dispatch + shared tail, per-job
count partitions) — over byte-compared outputs, min-of-N alternating
passes, and writes per-pass rows plus a summary row as JSONL (``--out``;
stdout otherwise).  The summary's ``packed_vs_serial``/``identical``
fields are the acceptance numbers; ``batch`` (occupancy, merged slabs,
shared wall) and ``decision`` (the serve_batch ledger record with its
prediction residual) are the why.  ``--cold`` adds the one-process-per-
job floor for scale.

Campaign usage (tools/tpu_campaign.sh step ``serve_batch``) tags the
artifact per round; the CPU-fallback harness proof lives at
campaign/serve_batch_r06_cpufallback.jsonl.

Usage: python tools/serve_batch.py [--jobs 16] [--reads 256]
       [--contig-len 5386] [--read-len 150] [--passes 5] [--cold]
       [--pileup scatter] [--out FILE.jsonl]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--jobs", type=int, default=16)
    ap.add_argument("--reads", type=int, default=256)
    ap.add_argument("--contig-len", type=int, default=5386)
    ap.add_argument("--read-len", type=int, default=150)
    ap.add_argument("--passes", type=int, default=5)
    ap.add_argument("--pileup", default="scatter",
                    choices=["auto", "scatter"])
    ap.add_argument("--cold", action="store_true",
                    help="also run the one-process-per-job cold floor")
    ap.add_argument("--cold-timeout", type=int, default=600)
    ap.add_argument("--out", default=None,
                    help="JSONL destination (default: stdout)")
    args = ap.parse_args(argv)

    from sam2consensus_tpu.utils.platform import pin_platform_from_env

    pin_platform_from_env()

    from sam2consensus_tpu.serve.benchmark import run_serve_batch_bench

    res = run_serve_batch_bench(
        n_jobs=args.jobs, n_reads=args.reads,
        contig_len=args.contig_len, read_len=args.read_len,
        passes=args.passes, pileup=args.pileup, cold=args.cold,
        cold_timeout=args.cold_timeout, log=log)
    lines = [json.dumps(r) for r in res["rows"]]
    lines.append(json.dumps(res["summary"]))
    blob = "\n".join(lines) + "\n"
    if args.out and args.out != "-":
        with open(args.out, "w") as fh:
            fh.write(blob)
        log(f"[serve_batch] wrote {args.out}")
    else:
        sys.stdout.write(blob)
    s = res["summary"]
    return 0 if (s["identical"] and s["warm_packed_min_sec"] > 0) else 1


if __name__ == "__main__":
    sys.exit(main())
