#!/bin/bash
# Detached TPU measurement campaign: waits for the tunnel, then runs the
# full evidence sequence (cpu-coexist check, bench, microbench, probe).
# Logs land in /root/repo/campaign/.
set -u
cd /root/repo
mkdir -p campaign
LOG=campaign/campaign.log
echo "$(date +%H:%M:%S) campaign start" >> "$LOG"

probe() {
  timeout -k 15 150 python -c "import jax; print(jax.devices()[0].platform)" \
      2>/dev/null | tail -1
}

# 1. wait for the tunnel (up to ~8.5h: 120 x (150s probe + grace + 90s))
up=0
for i in $(seq 1 120); do
  p=$(probe)
  if [ "$p" = "tpu" ]; then
    echo "$(date +%H:%M:%S) tunnel UP after $i tries" >> "$LOG"
    up=1
    break
  fi
  echo "$(date +%H:%M:%S) try $i: tunnel down" >> "$LOG"
  sleep 90
done
if [ "$up" != "1" ]; then
  echo "$(date +%H:%M:%S) giving up: tunnel never came up" >> "$LOG"
  exit 1
fi

# 2. cpu backend coexistence (the host-tail gate depends on it)
timeout -k 15 300 python -c "
import jax, numpy as np
print('default:', jax.default_backend(),
      [d.platform for d in jax.devices()])
try:
    cpus = jax.devices('cpu')
    x = jax.device_put(np.arange(8, dtype=np.int32), cpus[0])
    y = jax.jit(lambda a: a * 2)(x)
    print('cpu-routed jit OK:', np.asarray(y).tolist(), y.devices())
except Exception as e:
    print('NO CPU BACKEND:', type(e).__name__, e)
" > campaign/cpu_coexist_r05.txt 2>&1
echo "$(date +%H:%M:%S) cpu_coexist done" >> "$LOG"

# 3. full bench (all configs incl. north_star + wide_genome)
BENCH_INIT_TIMEOUT=300 BENCH_INIT_RETRIES=3 \
  timeout -k 30 5400 python bench.py > campaign/bench_preview_r05.json \
  2> campaign/bench_stderr_r05.log
rc=$?
echo "$(date +%H:%M:%S) bench done rc=$rc" >> "$LOG"

# 4. device-op microbench (pallas-vs-scatter evidence, mxu rates)
timeout -k 30 1800 python tools/microbench.py > campaign/microbench_tpu_r05.jsonl \
  2> campaign/microbench_stderr_r05.log
rc=$?
echo "$(date +%H:%M:%S) microbench done rc=$rc" >> "$LOG"

# 5. packed5 output-encoding measurement (sets S2C_P5_DEV_NS evidence)
timeout -k 30 1200 python tools/measure_p5.py > campaign/measure_p5_r05.jsonl \
  2> campaign/measure_p5_stderr_r05.log
rc=$?
echo "$(date +%H:%M:%S) measure_p5 done rc=$rc" >> "$LOG"

# 5b. fast-link placement artifact, on-chip half (VERDICT r4 #7): force
# PCIe-class constants so every placement gate flips device-side, and
# record the flipped decisions in measured bench rows (the real link is
# still the tunnel, so the absolute numbers are slow — the point is the
# rows' pileup/tail_device/encoding fields showing the coherent flip;
# the offline half is campaign/fastlink_matrix_r05.json)
S2C_TAIL_RT_MS=1 S2C_TAIL_LINK_MBPS=2000 S2C_LINK_PROBE=0 \
  BENCH_CONFIGS=ecoli_scale,wide_genome BENCH_WIDE_ORACLE_SHRINK=16 \
  BENCH_INIT_TIMEOUT=300 BENCH_INIT_RETRIES=3 \
  timeout -k 30 3600 python bench.py > campaign/fastlink_bench_r05.json \
  2> campaign/fastlink_bench_stderr_r05.log
rc=$?
echo "$(date +%H:%M:%S) fastlink bench done rc=$rc" >> "$LOG"

# 6. link probe (refresh PERF.md numbers)
timeout -k 30 900 python tools/tunnel_probe.py > campaign/tunnel_probe_r05.json \
  2> campaign/tunnel_probe_stderr_r05.log
rc=$?
echo "$(date +%H:%M:%S) probe done rc=$rc; campaign complete" >> "$LOG"
