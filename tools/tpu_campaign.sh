#!/bin/bash
# Detached TPU measurement campaign: waits for the tunnel, then runs the
# full evidence sequence (cpu-coexist check, bench, microbench, probe,
# chaos leg).  Logs land in /root/repo/campaign/.
#
# IDEMPOTENT / RESUMABLE (VERDICT r5 next-round #1): every step writes
# through a .partial file and promotes it to the r-tagged artifact only
# on rc=0, and a step whose artifact already exists is skipped — so a
# mid-campaign tunnel drop keeps the finished artifacts and a re-launch
# picks up at the first missing one.  CAMPAIGN_FORCE=1 redoes
# everything; CAMPAIGN_ROUND retags (default r05).
set -u
cd /root/repo
mkdir -p campaign
R=${CAMPAIGN_ROUND:-r06}
LOG=campaign/campaign.log
echo "$(date +%H:%M:%S) campaign start (round $R)" >> "$LOG"

probe() {
  timeout -k 15 150 python -c "import jax; print(jax.devices()[0].platform)" \
      2>/dev/null | tail -1
}

# run_step <name> <artifact> <stderr-log-or-"-"> <timeout-s> <cmd...>
# Skips when the artifact exists (unless CAMPAIGN_FORCE=1); writes
# stdout to <artifact>.partial and promotes on success.
run_step() {
  local name=$1 artifact=$2 errlog=$3 tmo=$4
  shift 4
  if [ -s "$artifact" ] && [ "${CAMPAIGN_FORCE:-0}" != "1" ]; then
    echo "$(date +%H:%M:%S) $name: SKIP ($artifact exists)" >> "$LOG"
    return 0
  fi
  local err=/dev/null
  [ "$errlog" != "-" ] && err=$errlog
  timeout -k 30 "$tmo" "$@" > "$artifact.partial" 2> "$err"
  local rc=$?
  if [ $rc -eq 0 ]; then
    mv "$artifact.partial" "$artifact"
  fi
  echo "$(date +%H:%M:%S) $name done rc=$rc" >> "$LOG"
  return $rc
}

# 1. wait for the tunnel (up to ~8.5h: 120 x (150s probe + grace + 90s))
up=0
for i in $(seq 1 120); do
  p=$(probe)
  if [ "$p" = "tpu" ]; then
    echo "$(date +%H:%M:%S) tunnel UP after $i tries" >> "$LOG"
    up=1
    break
  fi
  echo "$(date +%H:%M:%S) try $i: tunnel down" >> "$LOG"
  sleep 90
done
if [ "$up" != "1" ]; then
  echo "$(date +%H:%M:%S) giving up: tunnel never came up" >> "$LOG"
  exit 1
fi

# 1b. input-format fixtures (idempotent by construction: the tool
# verifies committed fixtures against a seeded regenerate and exits 1
# on drift — so this step doubles as the corpus-integrity check)
run_step format_fixtures "campaign/format_fixtures_$R.txt" - 600 \
  python tools/make_format_fixtures.py

# 2. cpu backend coexistence (the host-tail gate depends on it)
run_step cpu_coexist "campaign/cpu_coexist_$R.txt" - 300 python -c "
import jax, numpy as np
print('default:', jax.default_backend(),
      [d.platform for d in jax.devices()])
try:
    cpus = jax.devices('cpu')
    x = jax.device_put(np.arange(8, dtype=np.int32), cpus[0])
    y = jax.jit(lambda a: a * 2)(x)
    print('cpu-routed jit OK:', np.asarray(y).tolist(), y.devices())
except Exception as e:
    print('NO CPU BACKEND:', type(e).__name__, e)
"

# 3. full bench (all configs incl. north_star + wide_genome;
# BENCH_FULL_OUT writes the untruncated row set the regression gate
# reads directly.  BENCH_SERVE_JOBS=0: the cold-vs-warm serving
# numbers come from step 4e's dedicated serve_bench artifact — running
# the 8 cold subprocesses twice per round would double several minutes
# of wall clock for no extra signal.  BENCH_FLEET_JOBS=0 likewise:
# step 14's fleet_soak owns the queue-drain speedup artifact)
BENCH_INIT_TIMEOUT=300 BENCH_INIT_RETRIES=3 BENCH_SERVE_JOBS=0 \
  BENCH_INCR_PCT=0 BENCH_FLEET_JOBS=0 \
  BENCH_FULL_OUT="campaign/bench_preview_$R.full.json" \
  run_step bench "campaign/bench_preview_$R.json" \
  "campaign/bench_stderr_$R.log" 5400 python bench.py

# 4. device-op microbench (pallas-vs-scatter evidence, mxu rates)
run_step microbench "campaign/microbench_tpu_$R.jsonl" \
  "campaign/microbench_stderr_$R.log" 1800 python tools/microbench.py

# 4b. data-resident pallas pileup sweep (VERDICT r5 #2: the
# 735 Mcells/s / 8.8x R5.2 claim gets its own committed artifact —
# operands resident, kernel re-dispatched, median-of-3 runs per point)
run_step pallas_sweep "campaign/pallas_sweep_$R.jsonl" \
  "campaign/pallas_sweep_stderr_$R.log" 1800 python tools/pallas_sweep.py

# 4c. fused insertion-vote window calibration, median-of-3 (VERDICT r5
# #4: the 1e7 point flipped 0.77x/2.23x between single runs; the auto
# window re-pins from these medians, per-run samples in the artifact)
run_step ins_window "campaign/ins_window_$R.jsonl" \
  "campaign/ins_window_stderr_$R.log" 2400 python tools/ins_window_calibrate.py

# 4d. wire-codec A/B leg (R6 tentpole evidence): the same north-star
# device bench under each row codec; the delta8 row's util.h2d_mb vs
# the packed5 row's is the measured compression, and its
# pipeline/overlap_sec is the staging overlap claim
S2C_WIRE=packed5 S2C_SYNC_ACCUMULATE=1 BENCH_CONFIGS=north_star \
  BENCH_INIT_TIMEOUT=300 BENCH_INIT_RETRIES=3 \
  run_step wire_ab_packed5 "campaign/wire_ab_packed5_$R.json" \
  "campaign/wire_ab_packed5_stderr_$R.log" 3600 python bench.py
S2C_WIRE=delta8 S2C_SYNC_ACCUMULATE=1 BENCH_CONFIGS=north_star \
  BENCH_INIT_TIMEOUT=300 BENCH_INIT_RETRIES=3 \
  run_step wire_ab_delta8 "campaign/wire_ab_delta8_$R.json" \
  "campaign/wire_ab_delta8_stderr_$R.log" 3600 python bench.py

# 4e. cold-vs-warm serving benchmark (PR-5 serve tentpole evidence):
# >=8 small jobs per process-per-job baseline vs one warm ServeRunner,
# byte-compared; the summary row's speedup_vs_cold / jit hit counters
# are the warm-path claim.  CPU-fallback harness proof:
# campaign/serve_bench_r06_cpufallback.jsonl
run_step serve_bench "campaign/serve_bench_$R.jsonl" \
  "campaign/serve_bench_stderr_$R.log" 2400 \
  python tools/serve_bench.py --jobs 8

# 4f. input-format bench legs (formats tentpole evidence): the BAM
# ingest row vs its BGZF-SAM "equivalent gzip-SAM" twin (same corpus,
# one oracle) and the dense-indel long-read row — decode_sec per row is
# the block-parallel + binary-record claim, byte-identity per row the
# correctness gate.  CPU-fallback harness proof:
# perf/bench_formats_r06_cpufallback.json
BENCH_CONFIGS=ecoli_bam,longread_ont BENCH_SERVE_JOBS=0 \
  BENCH_INIT_TIMEOUT=300 BENCH_INIT_RETRIES=3 \
  BENCH_FULL_OUT="campaign/formats_bench_$R.full.json" \
  run_step formats_bench "campaign/formats_bench_$R.json" \
  "campaign/formats_bench_stderr_$R.log" 3600 python bench.py

# 4g. BGZF inflate thread scaling (decode-shard claim): raw ordered
# inflate MB/s + end-to-end ingest decode_sec at 1/2/4 threads, serial
# gzip control, host core count recorded.  CPU-fallback harness proof:
# perf/bgzf_scaling_r06_cpufallback.jsonl
run_step bgzf_scaling "campaign/bgzf_scaling_$R.jsonl" \
  "campaign/bgzf_scaling_stderr_$R.log" 1800 python tools/bgzf_scaling.py

# 4h. ingest thread scaling (the sharded-ingest claim, ISSUE 8): the
# byte-shard rung vs the streaming rung vs the serial floor at 1/2/4
# threads, a BAM binary-ingest leg, and the threaded native vote —
# best-of-5 per point, host core count stamped per row.  The committed
# bench-host artifact is perf/thread_scaling_r08.jsonl; this step
# re-measures on the rig so the r-tagged campaign copy tracks the
# hardware the other legs ran on.
run_step thread_scaling "campaign/thread_scaling_$R.jsonl" \
  "campaign/thread_scaling_stderr_$R.log" 1800 \
  python tools/thread_scaling.py

# 5. packed5 output-encoding measurement (sets S2C_P5_DEV_NS evidence)
run_step measure_p5 "campaign/measure_p5_$R.jsonl" \
  "campaign/measure_p5_stderr_$R.log" 1200 python tools/measure_p5.py

# 5b. fast-link placement artifact, on-chip half (VERDICT r4 #7): force
# PCIe-class constants so every placement gate flips device-side, and
# record the flipped decisions in measured bench rows (the real link is
# still the tunnel, so the absolute numbers are slow — the point is the
# rows' pileup/tail_device/encoding fields showing the coherent flip;
# the offline half is campaign/fastlink_matrix_$R.json)
S2C_TAIL_RT_MS=1 S2C_TAIL_LINK_MBPS=2000 S2C_LINK_PROBE=0 \
  BENCH_CONFIGS=ecoli_scale,wide_genome BENCH_WIDE_ORACLE_SHRINK=16 \
  BENCH_INIT_TIMEOUT=300 BENCH_INIT_RETRIES=3 \
  run_step fastlink_bench "campaign/fastlink_bench_$R.json" \
  "campaign/fastlink_bench_stderr_$R.log" 3600 python bench.py

# 6. link probe (refresh PERF.md numbers)
run_step tunnel_probe "campaign/tunnel_probe_$R.json" \
  "campaign/tunnel_probe_stderr_$R.log" 900 python tools/tunnel_probe.py

# 7. chaos-mode bench leg (resilience evidence): probabilistic fault
# injection across the device path with the degradation ladder armed.
# The rows' resilience/* and fault/* counters record the recovery story
# (retries, splits, demotions) while FASTA correctness is still gated
# by the bench's oracle comparison; deterministic via S2C_FAULT_SEED.
S2C_FAULT_INJECT="pileup_dispatch:rpc:p0.03,vote:rpc:p0.15,device_put:rpc:p0.02" \
  S2C_FAULT_SEED=7 S2C_ON_DEVICE_ERROR=fallback \
  BENCH_CONFIGS=ecoli_scale BENCH_INIT_TIMEOUT=300 BENCH_INIT_RETRIES=3 \
  run_step chaos_bench "campaign/chaos_bench_$R.json" \
  "campaign/chaos_bench_stderr_$R.log" 3600 python bench.py

# 8. chaos soak (serve survivability evidence): >=8 cycles of
# randomized SIGKILL / injected-hang / device-fault chaos against a
# journaled multi-job serve queue — per cycle: byte-identity vs a
# chaos-free baseline, journal fingerprint audit (zero lost / zero
# duplicated jobs), and bounded recovery time.  recovery_sec rides the
# regression gate: tools/regress_check.py --jsonl <artifact>
# --group-by mode --value recovery_sec.  CPU-fallback harness proof:
# campaign/chaos_soak_r06_cpufallback.jsonl
run_step chaos_soak "campaign/chaos_soak_$R.jsonl" \
  "campaign/chaos_soak_stderr_$R.log" 3600 \
  python tools/chaos_soak.py --cycles 8

# 9. differential ingest fuzz (hostile-input hardening evidence,
# ISSUE 9): seeded byte/field-level mutants over the fixture corpus,
# every mutant through the strict + tolerant rung matrices (serial /
# byte-shard / streaming gzip / pure-python + the BAM binary lanes) —
# the artifact's summary row must show 0 crashes / 0 hangs / 0
# strict-or-tolerant rung divergences.  The tier-1 smoke slice
# (tests/test_fuzz_smoke.py) keeps the guarantee live between
# campaigns; the committed proof is
# campaign/fuzz_ingest_r06_cpufallback.jsonl.  A second leg measures
# tolerant-mode overhead on CLEAN input (the <2% PERF.md claim):
# perf/tolerant_overhead_r06_cpufallback.json
run_step fuzz_ingest "campaign/fuzz_ingest_$R.jsonl" \
  "campaign/fuzz_ingest_stderr_$R.log" 3600 \
  python tools/fuzz_ingest.py --trials 1200 --no-progress --out -
run_step tolerant_overhead "campaign/tolerant_overhead_$R.json" \
  "campaign/tolerant_overhead_stderr_$R.log" 1200 \
  python tools/fuzz_ingest.py --overhead --out -

# 10. serve telemetry plane (fleet observability evidence, ISSUE 10):
# a journaled 8-job two-tenant queue with one job_hang-injected job,
# run telemetry-off then telemetry-on — the artifact's scrape rows
# show the exposition rewritten MID-HANG with growing heartbeat age
# (format-linted per scrape, counters monotone across scrapes), the
# summary row pins per-tenant e2e/queue_wait p50/p99 for both
# tenants, slo/violations burned exactly for the hung tenant, an
# on-demand profiler capture taken DURING the hang, and byte-identical
# outputs across the two passes.  The .prom sibling is the citable
# exposition snapshot (tools/check_perf_claims.py format-lints cited
# .prom evidence).  CPU-fallback harness proof:
# campaign/serve_telemetry_r06_cpufallback.jsonl
run_step serve_telemetry "campaign/serve_telemetry_$R.jsonl" \
  "campaign/serve_telemetry_stderr_$R.log" 1800 \
  python tools/serve_telemetry.py --jobs 8 \
  --prom-out "campaign/serve_telemetry_$R.prom"

# 11. continuous batching (cross-job slab packing evidence, ISSUE 11):
# one small-job queue through a warm runner serial (--batch off) vs
# packed (--batch N: shared slabs, one shared dispatch + shared tail,
# per-job count partitions), byte-compared, min-of-5 alternating
# passes + the cold-process floor.  The summary row's
# packed_vs_serial (jobs/sec ratio, target >=3x) and identical=true
# are the acceptance numbers; the decision row carries the serve_batch
# ledger prediction residual (must sit inside the drift band).  On a
# TPU rig this re-measures the real device-dispatch amortization the
# cpu-fallback proof can only approximate (its packed side routes the
# shared accumulation host-side per the link-free placement gate).
# CPU-fallback harness proof: campaign/serve_batch_r06_cpufallback.jsonl
run_step serve_batch "campaign/serve_batch_$R.jsonl" \
  "campaign/serve_batch_stderr_$R.log" 2400 \
  python tools/serve_batch.py --jobs 16 --reads 256 --passes 5 --cold \
  --out -

# 12. incremental consensus (count-resident serving evidence, ISSUE
# 13): +10% reads against a warm per-reference count cache vs the
# cold job over the combined input, byte-compared, min-of-3
# alternating passes through one warm runner.  The summary row's
# incr_cost_ratio (target <=0.15) and identical=true are the
# acceptance numbers; the count_cache decision row carries the ledger
# residual.  S2C_DECODE_MBPS_PER_CORE is the rig-calibration knob the
# decode model documents — the cpu-fallback rig decodes page-cache-
# warm input at ~1.2 GB/s/core where the bench rig's default is 330
# MB/s; without the calibration the warm delta job's decode_threads
# residual sits just outside the 4x band and manufactures a drift row.
# On a TPU rig this additionally measures the device-resident
# epilogue's d2h cut (wire/d2h_bytes in the job manifests) that the
# link-free proof cannot.  CPU-fallback harness proof:
# campaign/incremental_r06_cpufallback.jsonl
S2C_DECODE_MBPS_PER_CORE=1200 \
  run_step incremental "campaign/incremental_$R.jsonl" \
  "campaign/incremental_stderr_$R.log" 1800 \
  python tools/incremental_bench.py --reads 1000000 --passes 3 --out -

# 13. memory watermarks (ISSUE 14 memory plane): peak host+device
# bytes per config, one subprocess per config (ru_maxrss is a
# process-lifetime high-water mark), chunk-filling shapes so the
# capacity ledger decision's residual sits inside the drift band.
# On the TPU rig this additionally captures device memory_stats()
# peaks that the cpu-fallback proof cannot.  Gate the series with:
#   python tools/regress_check.py --jsonl campaign/mem_watermark_$R.jsonl \
#     --group-by config --value peak_rss_mb --lower-is-better
# CPU-fallback harness proof: campaign/mem_watermark_r06_cpufallback.jsonl
run_step mem_watermark "campaign/mem_watermark_$R.jsonl" \
  "campaign/mem_watermark_stderr_$R.log" 1800 \
  python tools/mem_watermark.py --out -

# 14. serve fleet soak (ISSUE 15 / ROADMAP 2(b) scale-out): N workers
# over ONE journal as a work-stealing queue — per cycle the rotation
# SIGKILL / SIGSTOP-wedge / persistent-fault must finish the queue
# byte-identical to a 1-worker chaos-free baseline with zero lost /
# zero duplicated jobs (journal fingerprint audit), and a dead or
# frozen worker's leased job must be re-claimed by a peer within 2x
# the lease TTL (steal_sec, measured from journal event timestamps).
# The speedup leg is the >=1.8x queue-drain target — meaningful on
# the multi-core rig; the cpu-fallback artifact records the 1-core
# harness truth (host_cores in the summary says which).  Gate:
#   python tools/regress_check.py --jsonl campaign/fleet_soak_$R.jsonl \
#     --group-by mode --value drain_sec --lower-is-better
# CPU-fallback harness proof: campaign/fleet_soak_r06_cpufallback.jsonl
run_step fleet_soak "campaign/fleet_soak_$R.jsonl" \
  "campaign/fleet_soak_stderr_$R.log" 3600 \
  python tools/fleet_soak.py

# 15. fleet flight recorder (ISSUE 16 observability): a fresh 2-worker
# journaled queue with one SIGKILL cycle, replayed by
# tools/fleet_trace.py into ONE Perfetto-loadable trace — per-job
# tracks must tile submit->commit gap-free (queue-wait / claim /
# steal-gap / run segments), the measured steal latency must sit
# within the fleet_soak 2x-lease-TTL bound, the s2c_sched_* queue-wait
# summary must be populated from journal timestamps, and the drained
# queue must stay byte-identical to a chaos-free baseline (the flight
# recorder observes; it must not perturb).  The leg JSONL's summary
# row is what check_perf_claims.py lints when cited.
# CPU-fallback harness proof: campaign/fleet_trace_r06_cpufallback.jsonl
run_step fleet_trace "campaign/fleet_trace_$R.jsonl" \
  "campaign/fleet_trace_stderr_$R.log" 1800 \
  python tools/fleet_trace.py --leg --out -

# 16. streaming-session chaos soak (ISSUE 17 / ROADMAP 2(c) live
# ingest): a journaled streaming session fed in read waves over the
# HTTP front door, with the serving worker SIGKILLed / SIGSTOP-wedged
# mid-session (journaled-but-unabsorbed backlog) or running under an
# injected session_wave_append fault (the count-bank crash window).
# Per cycle: the surviving peer must steal the session lease within
# 2x the lease TTL, replay every uncovered wave from its spool, keep
# serving the SAME sid to the retargeted client, and the final
# per-reference FASTA must be byte-identical to a one-shot batch run
# over the concatenated waves — with the journal wave audit showing
# zero lost / zero duplicated waves.  The summary row is what
# check_perf_claims.py lints when PERF.md cites the artifact.
# CPU-fallback harness proof: campaign/session_soak_r06_cpufallback.jsonl
run_step session_soak "campaign/session_soak_$R.jsonl" \
  "campaign/session_soak_stderr_$R.log" 3600 \
  python tools/session_soak.py

# 17. multi-host mesh scale-up (ISSUE 18 / ROADMAP 1 multichip): a
# procs x devs sweep where each point runs the FULL production jax
# backend over a process-spanning jax.distributed mesh (gloo is the
# DCN stand-in on CPU rigs) and must render FASTA byte-identical to
# the in-launcher CPU oracle.  Each row carries the capacity-planned
# admission story: the memory plane's plan_mesh_shards prices the job
# against a budget between the 1-host and 2-host per-host peaks, the
# real AdmissionController issues the "needs K hosts" mesh_shards
# verdict, and the predicted per-host bytes join the workers' measured
# tracked peak (capacity_in_band per S2C_DRIFT_BAND).  Gate the series:
#   python tools/regress_check.py --jsonl campaign/multihost_bench_$R.jsonl \
#     --group-by config --value wall_sec --lower-is-better
# CPU-fallback harness proof: campaign/multihost_bench_r06_cpufallback.jsonl
run_step multihost_bench "campaign/multihost_bench_$R.jsonl" \
  "campaign/multihost_bench_stderr_$R.log" 2400 \
  python tools/multihost_dryrun.py --bench --repeats 2 --out -

# 18. evidence plane what-if (ISSUE 19): a journaled two-round soak
# with a hung tenant and a worker restart, scored in hindsight —
# burn alerts must page exactly the hung tenant (replayed AND live
# after the restart), the rate card must survive the restart with its
# sample counts and age stamps intact, the scale hint's projected
# drain must join the journal-measured drain inside the recorded
# residual band, and output FASTA must be byte-identical with the
# plane dark.  One row per check + the summary row regress_check and
# check_perf_claims consume:
#   python tools/regress_check.py --jsonl campaign/fleet_whatif_$R.jsonl \
#     --group-by check --value measured_drain_sec --lower-is-better
# CPU-fallback harness proof: campaign/fleet_whatif_r06_cpufallback.jsonl
run_step fleet_whatif "campaign/fleet_whatif_$R.jsonl" \
  "campaign/fleet_whatif_stderr_$R.log" 1800 \
  python tools/fleet_whatif.py

# 19. cohort-scale batching (ISSUE 20): 10k shared-reference samples
# from ONE manifest submission streamed in occupancy-aware packed
# waves vs the PR-11 packed-stranger path (median-of-3) on the same
# job class.  The summary row's acceptance fields: identical (20
# random members byte-equal to a fresh serial runner),
# concordance_pinned (24-member mini-cohort concordance digest ==
# the CPU oracle's), replans_after_wave1 == 0 and
# new_compiles_after_wave1 == 0 (ONE PanelGeometry + one compile
# footprint cover every wave), residual_in_band (no cohort_wave
# decision drifted once its rate was learned), cohort_ge_stranger.
# Each cohort_wave row carries that wave's packed jobs/s and slab
# occupancy, so the regression gate compares the LAST wave against the
# earlier ones — a late-cohort rate collapse or occupancy decay fails
# the gate even when the summary roll-up still looks healthy:
#   python tools/regress_check.py --jsonl campaign/cohort_$R.jsonl \
#     --group-by mode --value jobs_per_sec
#   python tools/regress_check.py --jsonl campaign/cohort_$R.jsonl \
#     --group-by mode --value occupancy_pct
# CPU-fallback harness proof: campaign/cohort_r06_cpufallback.jsonl
run_step cohort "campaign/cohort_$R.jsonl" \
  "campaign/cohort_stderr_$R.log" 3600 \
  python tools/cohort_bench.py --samples 10000 --reads 64 \
  --contig-len 1500 --out -

echo "$(date +%H:%M:%S) campaign complete" >> "$LOG"
