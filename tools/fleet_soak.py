#!/usr/bin/env python3
"""Fleet soak: chaos against N work-stealing serve workers on ONE journal.

The fleet acceptance harness (ISSUE 15 / ROADMAP 2(b) scale-out): each
cycle launches ``--workers`` ``s2c serve --journal DIR --worker-id W``
subprocesses over the same journaled queue and injects one chaos mode
while they drain it —

* ``kill``   — SIGKILL one worker the moment it has a job in flight
               (its ``started`` event is the trigger); the survivors
               wait out the dead worker's lease TTL, reap it, re-claim
               the job from its checkpoint and finish the queue;
* ``wedge``  — SIGSTOP one worker mid-job instead: a FROZEN process
               renews nothing, so the same reap/steal path fires while
               the process still exists (the split-brain case — the
               victim, SIGCONT'd by the kernel or an operator, would
               find its lease gone and abandon its commit; here it is
               SIGKILL'd after the queue drains);
* ``fault``  — one worker runs with a persistent injected device fault
               (``pileup_dispatch:rpc:0:inf`` + fallback): its jobs
               demote to the host rung mid-run, the fleet keeps
               draining, bytes stay identical.

Every cycle asserts the fleet invariants:

1. **byte identity** — the cycle's output set is sha256-identical to a
   chaos-free single-worker baseline of the same queue;
2. **zero lost / zero duplicated** — the journal's fingerprint audit
   over the cycle's whole journal (claims/leases never weaken the
   exactly-once story);
3. **bounded takeover** — the victim's in-flight job is re-claimed by
   a peer within ``2 x --lease-ttl`` of the signal (``steal_sec``,
   measured from the journal's own event timestamps).

A ``speedup`` leg (serve/benchmark.run_fleet_bench) additionally
measures 1-worker vs N-worker queue-drain wall time — the ROADMAP 2(b)
>=1.8x target on a multi-core rig; the committed cpu-fallback artifact
records the 1-core harness truth (workers serialize on one core).

One JSON row per cycle + a summary row, as JSONL on stdout (or
``--out``); ``drain_sec`` rides the noise-aware regression gate
(``tools/regress_check.py --jsonl campaign/fleet_soak_<r>.jsonl
--group-by mode --value drain_sec --lower-is-better``).  Campaign step
14 (tools/tpu_campaign.sh); the cpu-fallback harness proof is
committed at campaign/fleet_soak_r06_cpufallback.jsonl, and
tools/check_perf_claims.py structurally validates any cited fleet_soak
JSONL (summary present, 0 lost / 0 duplicated / 0 failures).

Usage: python tools/fleet_soak.py [--cycles 6] [--jobs 4] [--workers 2]
       [--reads 12000] [--lease-ttl 2.5] [--out FILE.jsonl]
"""

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

MODES = ("kill", "wedge", "fault")


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def sha_dir(d):
    from sam2consensus_tpu.serve.benchmark import _sha_dir

    return _sha_dir(d)


def worker_cmd(inputs, outdir, jdir, worker, ttl, extra=()):
    cmd = [sys.executable, "-m", "sam2consensus_tpu.cli", "serve"]
    for p in inputs:
        cmd += ["-i", p]
    cmd += ["-o", outdir, "--journal", jdir, "--worker-id", worker,
            "--lease-ttl", str(ttl), "--pileup", "scatter", "--quiet",
            *extra]
    return cmd


def journal_events(jdir):
    """All readable events via the journal's own reader (it carries
    the multi-writer gap-retry logic a hand-rolled scan would miss)."""
    from sam2consensus_tpu.serve.journal import JobJournal

    if not os.path.isdir(jdir):
        return []
    try:
        return JobJournal(jdir, checkpoint_every=0).events()
    except OSError:
        return []


def wait_for_inflight(jdir, deadline):
    """(worker, key) of the first journal-visible in-flight job: a
    ``started`` event whose key has no terminal event yet."""
    while time.monotonic() < deadline:
        evs = journal_events(jdir)
        terminal = {e.get("key") for e in evs
                    if e.get("ev") in ("committed", "failed")}
        for e in evs:
            if e.get("ev") == "started" and e.get("worker") \
                    and e.get("key") not in terminal:
                return e["worker"], e["key"]
        time.sleep(0.025)
    return None, None


def steal_latency(jdir, key, victim, t_signal):
    """Seconds from the chaos signal to a peer's re-claim of ``key``
    (journal event wall-clock timestamps)."""
    for e in journal_events(jdir):
        if e.get("ev") == "claimed" and e.get("key") == key \
                and e.get("worker") != victim \
                and float(e.get("t", 0)) >= t_signal:
            return round(float(e["t"]) - t_signal, 3)
    return None


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--cycles", type=int, default=6)
    ap.add_argument("--jobs", type=int, default=4)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--reads", type=int, default=12000)
    ap.add_argument("--contig-len", type=int, default=5000)
    ap.add_argument("--read-len", type=int, default=100)
    ap.add_argument("--lease-ttl", type=float, default=2.5)
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: a fresh tempdir)")
    ap.add_argument("--per-process-timeout", type=float, default=600.0)
    ap.add_argument("--skip-speedup", action="store_true",
                    help="omit the 1-vs-N drain-speedup leg")
    ap.add_argument("--out", default=None,
                    help="JSONL destination (default: stdout)")
    args = ap.parse_args(argv)
    if args.workers < 2:
        ap.error("--workers must be >= 2 (stealing needs a peer)")

    import tempfile

    from sam2consensus_tpu.serve.journal import JobJournal
    from sam2consensus_tpu.utils.simulate import SimSpec, simulate

    work = args.workdir or tempfile.mkdtemp(prefix="s2c_fleet_")
    os.makedirs(work, exist_ok=True)
    log(f"[fleet_soak] workdir {work}")

    inputs = []
    for k in range(args.jobs):
        spec = SimSpec(n_contigs=1, contig_len=args.contig_len,
                       n_reads=args.reads, read_len=args.read_len,
                       contig_len_jitter=0.0, seed=7300 + k,
                       contig_prefix=f"fl{k:02d}_")
        p = os.path.join(work, f"job{k}.sam")
        with open(p, "w") as fh:
            fh.write(simulate(spec))
        inputs.append(p)

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # one persistent compile cache for the whole soak: cycles measure
    # coordination + recovery, not XLA re-compilation
    env["S2C_JIT_CACHE"] = os.path.join(work, "_jit_cache")

    # chaos-free baseline: the byte-identity oracle for every cycle
    base_out = os.path.join(work, "out_base")
    t0 = time.monotonic()
    r = subprocess.run(worker_cmd(inputs, base_out,
                                  os.path.join(work, "j_base"),
                                  "base0", args.lease_ttl),
                       env=env, capture_output=True, text=True,
                       timeout=args.per_process_timeout)
    base_sec = time.monotonic() - t0
    if r.returncode != 0:
        log(f"[fleet_soak] baseline failed rc={r.returncode}:\n"
            f"{r.stderr[-2000:]}")
        return 2
    want = sha_dir(base_out)
    log(f"[fleet_soak] baseline {base_sec:.1f}s, "
        f"{len(want)} output file(s)")

    rows = []
    failures = 0
    bound = 2 * args.lease_ttl
    for c in range(args.cycles):
        mode = MODES[c % len(MODES)]
        outdir = os.path.join(work, f"out_c{c}")
        jdir = os.path.join(work, f"j_c{c}")
        for d in (outdir, jdir):
            shutil.rmtree(d, ignore_errors=True)
        workers = [f"fw{i}" for i in range(args.workers)]
        procs = {}
        t_start = time.monotonic()
        for i, w in enumerate(workers):
            extra = ()
            if mode == "fault" and i == 0:
                extra = ("--fault-inject", "pileup_dispatch:rpc:0:inf",
                         "--on-device-error", "fallback",
                         "--retries", "1", "--retry-backoff", "0.01")
            procs[w] = subprocess.Popen(
                worker_cmd(inputs, outdir, jdir, w, args.lease_ttl,
                           extra),
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL)
        victim = None
        steal_sec = None
        t_signal = None
        signaled = False
        if mode in ("kill", "wedge"):
            deadline = time.monotonic() + args.per_process_timeout
            victim, vkey = wait_for_inflight(jdir, deadline)
            if victim is not None and victim in procs:
                t_signal = time.time()
                procs[victim].send_signal(
                    signal.SIGKILL if mode == "kill"
                    else signal.SIGSTOP)
                signaled = True
                log(f"[fleet_soak] c{c} {mode}: "
                    f"{'killed' if mode == 'kill' else 'froze'} "
                    f"{victim} holding {vkey}")
        rc = 0
        for w, pr in procs.items():
            if mode == "wedge" and w == victim:
                continue                # frozen: reaped below
            try:
                pr.wait(timeout=args.per_process_timeout)
            except subprocess.TimeoutExpired:
                pr.kill()
                pr.wait(timeout=30)
                rc = rc or -1
            if w != victim or mode not in ("kill", "wedge"):
                rc = rc or pr.returncode
        if mode == "wedge" and victim in procs:
            # the frozen victim served its purpose; put it down
            procs[victim].send_signal(signal.SIGKILL)
            procs[victim].wait(timeout=30)
        drain_sec = time.monotonic() - t_start
        if signaled:
            steal_sec = steal_latency(jdir, vkey, victim, t_signal)

        got = sha_dir(outdir) if os.path.isdir(outdir) else {}
        identical = got == want
        audit = JobJournal(jdir).audit()
        lost, dup = audit["lost"], audit["duplicated"]
        signal_late = False
        if signaled and steal_sec is None:
            # the victim may have committed the watched job in the gap
            # between our journal scan and the signal landing (jobs
            # are only seconds long): that degenerates the cycle to a
            # plain kill-after-commit — the queue invariants below
            # still hold, but there was no steal to time
            signal_late = any(
                e.get("ev") == "committed" and e.get("key") == vkey
                and e.get("worker") == victim
                for e in journal_events(jdir))
        steal_ok = ((steal_sec is not None and steal_sec <= bound)
                    or signal_late) if signaled else True
        ok = (rc == 0 and identical and not lost and not dup
              and steal_ok)
        failures += 0 if ok else 1
        row = {"cycle": c, "mode": mode, "ok": ok, "rc": rc,
               "workers": args.workers, "jobs": args.jobs,
               "drain_sec": round(drain_sec, 3),
               "identical": identical,
               "lost": len(lost), "duplicated": len(dup),
               "committed": len(audit["commit_counts"]),
               "victim": victim, "steal_sec": steal_sec,
               "signal_late": signal_late,
               "steal_bound_sec": bound}
        rows.append(row)
        log(f"[fleet_soak] c{c} {mode}: " + ("OK" if ok else "FAIL")
            + f" drain {drain_sec:.1f}s"
            + (f" steal {steal_sec}s (bound {bound}s)"
               if steal_sec is not None else ""))

    speedup_summary = None
    if not args.skip_speedup:
        from sam2consensus_tpu.serve.benchmark import run_fleet_bench

        res = run_fleet_bench(n_jobs=args.jobs,
                              n_reads=args.reads,
                              contig_len=args.contig_len,
                              read_len=args.read_len,
                              n_workers=args.workers,
                              lease_ttl=max(args.lease_ttl, 10.0),
                              per_process_timeout=args
                              .per_process_timeout, log=log)
        for rr in res["rows"]:
            rows.append({"cycle": "speedup", **rr,
                         "ok": res["summary"]["ok"]})
        speedup_summary = res["summary"]
        failures += 0 if res["summary"]["ok"] else 1

    steals = [r["steal_sec"] for r in rows
              if r.get("steal_sec") is not None]
    summary = {
        "mode": "summary",
        "cycles": args.cycles, "jobs": args.jobs,
        "workers": args.workers, "reads": args.reads,
        "lease_ttl_sec": args.lease_ttl,
        "identical_all": all(r.get("identical", True) for r in rows),
        "lost_total": sum(r.get("lost", 0) for r in rows),
        "duplicated_total": sum(r.get("duplicated", 0) for r in rows),
        "signaled_cycles": sum(1 for r in rows
                               if r.get("victim") is not None),
        "max_steal_sec": max(steals) if steals else None,
        "steal_bound_sec": bound,
        "baseline_sec": round(base_sec, 3),
        "drain_speedup": speedup_summary["drain_speedup"]
        if speedup_summary else None,
        "serial_drain_sec": speedup_summary["serial_drain_sec"]
        if speedup_summary else None,
        "fleet_drain_sec": speedup_summary["fleet_drain_sec"]
        if speedup_summary else None,
        "host_cores": os.cpu_count(),
        "failures": failures,
        "platform": os.environ.get("JAX_PLATFORMS", ""),
    }
    lines = [json.dumps(r) for r in rows] + [json.dumps(summary)]
    blob = "\n".join(lines) + "\n"
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(blob)
        log(f"[fleet_soak] wrote {args.out}")
    else:
        sys.stdout.write(blob)
    if not args.workdir:
        shutil.rmtree(work, ignore_errors=True)
    return 0 if failures == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
