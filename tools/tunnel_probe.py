#!/usr/bin/env python3
"""Characterize the host<->device link and the tail ops' real costs.

Run on the tunneled TPU to answer: how much of vote_sec / accumulate_sec
is (a) dispatch round-trip latency, (b) transfer bytes, (c) device compute.
Prints one human-readable line per measurement to stderr and a JSON summary
to stdout.
"""
import json
import sys
import time

import numpy as np

sys.path.insert(0, __import__("os").path.dirname(
    __import__("os").path.abspath(__file__)) + "/..")


def timed(fn, n=5):
    fn()  # warm
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return min(ts), sorted(ts)[len(ts) // 2]


def main():
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    out = {"platform": dev.platform}
    log = lambda m: print(m, file=sys.stderr, flush=True)

    # 1. null dispatch + scalar fetch round trip
    one = jnp.ones((8,), jnp.int32)
    f = jax.jit(lambda x: x + 1)
    mn, md = timed(lambda: np.asarray(f(one)))
    out["rt_null_ms"] = round(md * 1e3, 2)
    log(f"null dispatch+fetch: min {mn*1e3:.1f}ms median {md*1e3:.1f}ms")

    # 2. h2d bandwidth
    for mb in (1, 16, 64):
        a = np.random.randint(0, 250, (mb << 20,), dtype=np.uint8)
        mn, md = timed(lambda: jax.device_put(a).block_until_ready())
        out[f"h2d_{mb}mb_ms"] = round(md * 1e3, 1)
        log(f"h2d {mb}MB: {md*1e3:.1f}ms ({mb/md:.0f} MB/s)")

    # 3. d2h bandwidth
    for mb in (1, 16, 64):
        d = jax.device_put(np.zeros((mb << 20,), dtype=np.uint8))
        d.block_until_ready()
        mn, md = timed(lambda: np.asarray(d))
        out[f"d2h_{mb}mb_ms"] = round(md * 1e3, 1)
        log(f"d2h {mb}MB: {md*1e3:.1f}ms ({mb/md:.0f} MB/s)")

    # 4. vote_block on-device at ecoli scale (scalar-forced execution;
    #    block_until_ready returns early over the tunnel)
    from sam2consensus_tpu.ops.cutoff import encode_thresholds
    from sam2consensus_tpu.ops.vote import vote_block
    L = 4_600_000
    counts = jax.device_put(
        np.random.randint(0, 40, (L, 6), dtype=np.int32))
    thr = jax.device_put(encode_thresholds([0.25]))
    vb = jax.jit(vote_block, static_argnames=("min_depth",))
    vbs = jax.jit(lambda c, t: vote_block(c, t, 1)[0].sum())
    mn, md = timed(lambda: np.asarray(vbs(counts, thr)))
    out["vote_4p6m_dev_ms"] = round(md * 1e3, 1)
    log(f"vote_block L=4.6M -> scalar: {md*1e3:.1f}ms")

    # 5. vote + fetch syms only
    mn, md = timed(lambda: np.asarray(vb(counts, thr, min_depth=1)[0]))
    out["vote_4p6m_fetch_ms"] = round(md * 1e3, 1)
    log(f"vote_block L=4.6M +fetch syms(4.6MB): {md*1e3:.1f}ms")

    # 6. coverage + full-cov fetch (the current tail's first round trip)
    from sam2consensus_tpu.ops import fused
    mn, md = timed(lambda: np.asarray(fused.coverage(counts)))
    out["cov_fetch_4p6m_ms"] = round(md * 1e3, 1)
    log(f"coverage+fetch int32[4.6M] (18MB): {md*1e3:.1f}ms")

    # 7. scatter slab: 65536 rows x 128 wide (one SCATTER_CELL_BUDGET slab)
    from sam2consensus_tpu.ops.pileup import _scatter_segments
    rows, w = 65536, 128
    starts = np.random.randint(0, L - 200, rows).astype(np.int32)
    codes = np.random.randint(0, 6, (rows, w), dtype=np.uint8)
    cbuf = jax.device_put(np.zeros((L + 8, 6), np.int32))

    def scat():
        nonlocal cbuf
        cbuf = _scatter_segments(cbuf, jnp.asarray(starts),
                                 jnp.asarray(codes), L)
        cbuf.block_until_ready()
    mn, md = timed(scat)
    out["scatter_slab_ms"] = round(md * 1e3, 1)
    log(f"scatter slab 64k x 128 (8.4MB h2d + scatter): {md*1e3:.1f}ms "
        f"({rows*w/md/1e6:.0f} Mcells/s end-to-end)")

    # 8. device-side transfer-free scatter (same slab resident)
    dstarts = jax.device_put(starts)
    dcodes = jax.device_put(codes)
    jax.block_until_ready((dstarts, dcodes))

    def scat_res():
        nonlocal cbuf
        cbuf = _scatter_segments(cbuf, dstarts, dcodes, L)
        cbuf.block_until_ready()
    mn, md = timed(scat_res)
    out["scatter_slab_resident_ms"] = round(md * 1e3, 1)
    log(f"scatter slab resident (no h2d): {md*1e3:.1f}ms "
        f"({rows*w/md/1e6:.0f} Mcells/s device)")

    # 9. dispatch of vote_packed-sized jit without fetch, measuring dispatch
    #    overhead of a big fused call
    t0 = time.perf_counter()
    r = vb(counts, thr, min_depth=1)
    disp = time.perf_counter() - t0
    jax.block_until_ready(r)
    out["vote_dispatch_only_ms"] = round(disp * 1e3, 1)
    log(f"vote dispatch (async, no block): {disp*1e3:.1f}ms")

    print(json.dumps(out))


if __name__ == "__main__":
    main()
