#!/usr/bin/env python3
"""Device-op microbenchmarks: the evidence base for kernel defaults.

Times the competing implementations of the two hot device ops on the
current JAX default device and prints one JSON object per line:

* pileup: XLA scatter-add vs MXU one-hot matmul in both transfer layouts
  (padded TilePlan vs compact SlotPlan) — end-to-end per slab, split into
  host planning / host->device transfer / device compute so a tunnel-
  bottlenecked link is visible instead of inferred (round 1 shipped the
  MXU path default-off because the padded layout lost end-to-end while
  winning on-device; this harness is how that decision gets re-made on
  numbers).
* insertion table: XLA scatter build vs the Pallas segmented-reduce
  kernel, on an insertion-heavy amplicon-like event mix.

Run on real hardware:  python tools/microbench.py
CI / no accelerator:   JAX_PLATFORMS=cpu python tools/microbench.py
Knobs: MB_ROWS (default 65536), MB_WIDTH (128), MB_GENOME (4600000),
MB_REPEATS (5), MB_INS_SITES (20000), MB_INS_EVENTS (2000000).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sam2consensus_tpu.utils.platform import pin_platform_from_env  # noqa: E402
pin_platform_from_env()

import numpy as np  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def emit(**kw):
    print(json.dumps(kw), flush=True)


def timed(fn, repeats):
    """Median wall seconds over ``repeats`` calls (after the caller's
    warm-up), forcing completion with a one-element fetch.

    ``block_until_ready`` returns EARLY over the axon tunnel (measured:
    a 47 ms vote "completes" in 0.0 ms, then the first fetch pays it —
    tools/tunnel_probe.py), so every repeat fetches one element of the
    first output leaf instead.  That adds one ~65 ms round trip per
    repeat, identically for every variant being compared.
    """
    ts = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = fn()
        leaf = jax.tree_util.tree_leaves(out)[0]
        np.asarray(leaf.ravel()[0])
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts)), out


def bench_pileup(rows, width, genome_len, repeats):
    from sam2consensus_tpu.constants import NUM_SYMBOLS
    from sam2consensus_tpu.ops import mxu_pileup
    from sam2consensus_tpu.ops.pileup import _scatter_segments

    rng = np.random.default_rng(7)
    tile = mxu_pileup.TILE_POSITIONS
    padded_len = -(-(genome_len + 1) // tile) * tile
    starts = rng.integers(0, genome_len - width, rows).astype(np.int32)
    codes = rng.integers(0, 6, (rows, width)).astype(np.uint8)
    codes[rng.random(codes.shape) < 0.05] = 255
    cells = rows * width

    counts = jnp.zeros((padded_len, NUM_SYMBOLS), dtype=jnp.int32)

    # --- scatter ---------------------------------------------------------
    s_dev = jax.device_put(starts)
    c_dev = jax.device_put(codes)
    _ = _scatter_segments(counts, s_dev, c_dev, genome_len)  # warm compile
    counts = jnp.zeros((padded_len, NUM_SYMBOLS), dtype=jnp.int32)

    def run_scatter():
        s = jax.device_put(starts)
        c = jax.device_put(codes)
        return _scatter_segments(jnp.zeros((padded_len, NUM_SYMBOLS),
                                           jnp.int32), s, c, genome_len)

    t_scatter, out_scatter = timed(run_scatter, repeats)
    emit(op="pileup", impl="scatter", rows=rows, width=width,
         genome_len=genome_len, sec=round(t_scatter, 5),
         wire_bytes=int(starts.nbytes + codes.nbytes),
         cells_per_sec=round(cells / t_scatter))

    # --- scatter, 4-bit packed wire (production path) ---------------------
    from sam2consensus_tpu.ops.pileup import (_scatter_segments_packed,
                                              pack_nibbles)

    packed_host = pack_nibbles(codes)
    _ = _scatter_segments_packed(
        jnp.zeros((padded_len, NUM_SYMBOLS), jnp.int32),
        jax.device_put(starts), jax.device_put(packed_host), genome_len)

    def run_scatter_packed():
        pk = pack_nibbles(codes)          # host pack is part of the cost
        return _scatter_segments_packed(
            jnp.zeros((padded_len, NUM_SYMBOLS), jnp.int32),
            jax.device_put(starts), jax.device_put(pk), genome_len)

    t_packed, out_packed = timed(run_scatter_packed, repeats)
    emit(op="pileup", impl="scatter_packed", rows=rows, width=width,
         genome_len=genome_len, sec=round(t_packed, 5),
         wire_bytes=int(starts.nbytes + packed_host.nbytes),
         cells_per_sec=round(cells / t_packed))

    # --- mxu, padded transfer (round-1 layout) ---------------------------
    plan = mxu_pileup.plan_tiles(starts, codes, padded_len, tile,
                                 max_blowup=float("inf"))
    _ = mxu_pileup.pileup_mxu(
        jnp.zeros((padded_len, NUM_SYMBOLS), jnp.int32),
        jnp.asarray(plan.loc), jnp.asarray(plan.codes), tile=tile,
        n_tiles=plan.n_tiles, rows_per_tile=plan.rows_per_tile,
        width=width)

    def run_padded():
        p = mxu_pileup.plan_tiles(starts, codes, padded_len, tile,
                                  max_blowup=float("inf"))
        return mxu_pileup.pileup_mxu(
            jnp.zeros((padded_len, NUM_SYMBOLS), jnp.int32),
            jnp.asarray(p.loc), jnp.asarray(p.codes), tile=tile,
            n_tiles=p.n_tiles, rows_per_tile=p.rows_per_tile, width=width)

    t_padded, out_padded = timed(run_padded, repeats)
    t_plan0 = time.perf_counter()
    for _ in range(repeats):
        mxu_pileup.plan_tiles(starts, codes, padded_len, tile,
                              max_blowup=float("inf"))
    plan_padded_sec = (time.perf_counter() - t_plan0) / repeats
    emit(op="pileup", impl="mxu_padded", rows=rows, width=width,
         genome_len=genome_len, sec=round(t_padded, 5),
         host_plan_sec=round(plan_padded_sec, 5),
         wire_bytes=int(plan.loc.nbytes + plan.codes.nbytes),
         blowup=round(plan.blowup, 2),
         cells_per_sec=round(cells / t_padded))

    # --- mxu, compact transfer (slot layout) -----------------------------
    sp = mxu_pileup.plan_slots(starts, width, padded_len, tile,
                               max_blowup=float("inf"))
    _ = mxu_pileup.pileup_mxu_compact(
        jnp.zeros((padded_len, NUM_SYMBOLS), jnp.int32),
        jnp.asarray(starts), jnp.asarray(codes), jnp.asarray(sp.slot),
        tile=tile, n_tiles=sp.n_tiles, rows_per_tile=sp.rows_per_tile,
        width=width)

    def run_compact():
        p = mxu_pileup.plan_slots(starts, width, padded_len, tile,
                                  max_blowup=float("inf"))
        return mxu_pileup.pileup_mxu_compact(
            jnp.zeros((padded_len, NUM_SYMBOLS), jnp.int32),
            jnp.asarray(starts), jnp.asarray(codes), jnp.asarray(p.slot),
            tile=tile, n_tiles=p.n_tiles, rows_per_tile=p.rows_per_tile,
            width=width)

    t_compact, out_compact = timed(run_compact, repeats)
    t_plan0 = time.perf_counter()
    for _ in range(repeats):
        mxu_pileup.plan_slots(starts, width, padded_len, tile,
                              max_blowup=float("inf"))
    plan_compact_sec = (time.perf_counter() - t_plan0) / repeats
    emit(op="pileup", impl="mxu_compact", rows=rows, width=width,
         genome_len=genome_len, sec=round(t_compact, 5),
         host_plan_sec=round(plan_compact_sec, 5),
         wire_bytes=int(starts.nbytes + codes.nbytes + sp.slot.nbytes),
         blowup=round(sp.blowup, 2),
         cells_per_sec=round(cells / t_compact))

    # --- pallas tile-CSR histogram (round-5 production kernel) -----------
    from sam2consensus_tpu.ops import pallas_pileup as pp

    interp = jax.default_backend() != "tpu"
    pl_tile = pp.TILE_POSITIONS
    pl_padded = -(-(genome_len + 1) // pl_tile) * pl_tile

    def run_pallas():
        plan = pp.plan_rows(starts.astype(np.int64), width, pl_padded,
                            pl_tile)
        pk = pack_nibbles(codes)
        return pp.pileup_pallas_packed(
            jnp.zeros((genome_len + 1, NUM_SYMBOLS), jnp.int32),
            jax.device_put(starts), jax.device_put(pk),
            jax.device_put(plan.rank), tile=pl_tile,
            n_tiles=plan.n_tiles, width=width,
            row_block=plan.row_block, max_blocks=plan.max_blocks,
            n_rows_padded=plan.n_rows_padded,
            blk_lo=jax.device_put(plan.blk_lo),
            blk_n=jax.device_put(plan.blk_n), interpret=interp)

    _ = run_pallas()
    t_pallas, out_pallas = timed(run_pallas, repeats)
    t_plan0 = time.perf_counter()
    for _ in range(repeats):
        pp.plan_rows(starts.astype(np.int64), width, pl_padded, pl_tile)
    plan_pallas_sec = (time.perf_counter() - t_plan0) / repeats
    emit(op="pileup", impl="pallas_csr", rows=rows, width=width,
         genome_len=genome_len, sec=round(t_pallas, 5), interpret=interp,
         host_plan_sec=round(plan_pallas_sec, 5),
         wire_bytes=int(starts.nbytes + packed_host.nbytes + 4 * rows),
         cells_per_sec=round(cells / t_pallas))

    same = (np.array_equal(np.asarray(out_scatter)[:genome_len],
                           np.asarray(out_padded)[:genome_len])
            and np.array_equal(np.asarray(out_scatter)[:genome_len],
                               np.asarray(out_compact)[:genome_len])
            and np.array_equal(np.asarray(out_scatter)[:genome_len],
                               np.asarray(out_packed)[:genome_len])
            and np.array_equal(np.asarray(out_scatter)[:genome_len],
                               np.asarray(out_pallas)[:genome_len]))
    emit(op="pileup", check="all_impls_equal", ok=bool(same))
    return {"scatter": t_scatter, "mxu_padded": t_padded,
            "mxu_compact": t_compact, "pallas_csr": t_pallas}


def bench_insertion(n_sites, n_events, repeats):
    from sam2consensus_tpu.ops import pallas_insertion
    from sam2consensus_tpu.ops.insertions import build_insertion_table

    rng = np.random.default_rng(11)
    max_cols = 8
    ev_key = np.sort(rng.integers(0, n_sites, n_events)).astype(np.int32)
    ev_col = rng.integers(0, max_cols, n_events).astype(np.int32)
    ev_code = rng.integers(0, 6, n_events).astype(np.int32)

    kp = 1 << max(1, (n_sites + 1 - 1).bit_length())
    cp = 1 << max(1, (max_cols - 1).bit_length())

    def run_scatter():
        table = jnp.zeros((kp, cp, 6), dtype=jnp.int32)
        return build_insertion_table(table, jnp.asarray(ev_key),
                                     jnp.asarray(ev_col),
                                     jnp.asarray(ev_code))

    _ = run_scatter()
    t_scatter, out_scatter = timed(run_scatter, repeats)
    emit(op="insertion_table", impl="scatter", sites=n_sites,
         events=n_events, sec=round(t_scatter, 5),
         events_per_sec=round(n_events / t_scatter))

    interp = jax.default_backend() != "tpu"

    def run_pallas():
        return pallas_insertion.build_insertion_table_pallas(
            ev_key, ev_col, ev_code, kp, cp, interpret=interp)

    _ = run_pallas()
    t_pallas, out_pallas = timed(run_pallas, repeats)
    emit(op="insertion_table", impl="pallas", sites=n_sites,
         events=n_events, sec=round(t_pallas, 5), interpret=interp,
         events_per_sec=round(n_events / t_pallas))

    same = np.array_equal(np.asarray(out_scatter),
                          np.asarray(out_pallas))
    emit(op="insertion_table", check="all_impls_equal", ok=bool(same))

    # --- FULL insertion tail: scatter table + XLA vote vs the fused
    # in-kernel vote (round-4 verdict #2: the table never leaves VMEM)
    from sam2consensus_tpu.ops.cutoff import encode_thresholds
    from sam2consensus_tpu.ops.insertions import vote_insertions

    site_cov = rng.integers(0, 200, kp).astype(np.int32)
    n_cols = np.full(kp, max_cols, dtype=np.int32)
    thr = encode_thresholds([0.25])

    def run_scatter_tail():
        table = jnp.zeros((kp, cp, 6), dtype=jnp.int32)
        table = build_insertion_table(table, jnp.asarray(ev_key),
                                      jnp.asarray(ev_col),
                                      jnp.asarray(ev_code))
        return vote_insertions(table, jnp.asarray(site_cov),
                               jnp.asarray(n_cols), jnp.asarray(thr))

    _ = run_scatter_tail()
    t_stail, out_stail = timed(run_scatter_tail, repeats)
    emit(op="insertion_tail", impl="scatter+vote", sites=n_sites,
         events=n_events, sec=round(t_stail, 5),
         events_per_sec=round(n_events / t_stail))

    eplan = pallas_insertion.plan_events(ev_key, ev_col, ev_code,
                                         n_sites, cp)
    kmin = min(kp, eplan.kp)
    sc_p = np.zeros(eplan.kp, np.int32)
    sc_p[:kmin] = site_cov[:kmin]
    nc_p = np.zeros(eplan.kp, np.int32)
    nc_p[:kmin] = n_cols[:kmin]

    def run_fused_tail():
        return pallas_insertion.vote_insertions_pallas(
            eplan, sc_p, nc_p, thr, cp, interpret=interp)

    _ = run_fused_tail()
    t_ftail, out_ftail = timed(run_fused_tail, repeats)
    emit(op="insertion_tail", impl="fused_vote", sites=n_sites,
         events=n_events, sec=round(t_ftail, 5), interpret=interp,
         events_per_sec=round(n_events / t_ftail))
    same_tail = np.array_equal(np.asarray(out_stail)[:, :kmin, :],
                               np.asarray(out_ftail)[:, :kmin, :])
    emit(op="insertion_tail", check="fused_equals_scatter",
         ok=bool(same_tail))
    return {"scatter": t_scatter, "pallas": t_pallas,
            "scatter_tail": t_stail, "fused_tail": t_ftail}


def main():
    rows = int(os.environ.get("MB_ROWS", "65536"))
    width = int(os.environ.get("MB_WIDTH", "128"))
    genome = int(os.environ.get("MB_GENOME", "4600000"))
    repeats = int(os.environ.get("MB_REPEATS", "5"))
    ins_sites = int(os.environ.get("MB_INS_SITES", "20000"))
    ins_events = int(os.environ.get("MB_INS_EVENTS", "2000000"))

    dev = jax.devices()[0]
    emit(op="env", platform=dev.platform, device_kind=dev.device_kind,
         n_devices=len(jax.devices()))
    p = bench_pileup(rows, width, genome, repeats)
    i = bench_insertion(ins_sites, ins_events, repeats)
    # insertion-kernel decision sweep (VERDICT r2 #4): pallas vs scatter
    # across event scales, from a phiX-like trickle to amplicon-heavy.
    # Off by default away from TPU: the large cases in interpret-mode
    # Pallas multiply CPU wall time severalfold.
    sweep = {}
    sweep_default = "1" if jax.default_backend() == "tpu" else "0"
    if os.environ.get("MB_INS_SWEEP", sweep_default) != "0":
        for sites, events in ((500, 20_000), (5_000, 200_000),
                              (20_000, 2_000_000), (50_000, 8_000_000),
                              (100_000, 10_000_000)):
            if (sites, events) == (ins_sites, ins_events):
                sweep[(sites, events)] = i
                continue
            sweep[(sites, events)] = bench_insertion(sites, events, repeats)
        wins = {f"{s}x{e}": round(r["scatter"] / r["pallas"], 2)
                for (s, e), r in sweep.items()}
        emit(op="insertion_sweep", pallas_speedup_vs_scatter=wins)
        # the decision-relevant ratio (round-4 verdict #2): FULL tail,
        # fused in-kernel vote vs scatter table + XLA vote
        tail_wins = {f"{s}x{e}":
                     round(r["scatter_tail"] / r["fused_tail"], 2)
                     for (s, e), r in sweep.items()}
        emit(op="insertion_tail_sweep",
             fused_speedup_vs_scatter_tail=tail_wins)
    emit(op="summary",
         pileup_winner=min(p, key=p.get),
         pileup_speedup_vs_scatter=round(p["scatter"] / min(p.values()), 2),
         insertion_winner=min(i, key=i.get),
         insertion_speedup_vs_scatter=round(
             i["scatter"] / min(i.values()), 2))


if __name__ == "__main__":
    main()
