#!/usr/bin/env python3
"""Placement-gate decision matrix: tunnel-class vs PCIe-class links.

Round-4 verdict #7: the placement model's device-side story on a fast
link rested on the cost model alone — no committed artifact showed the
gates flipping.  This tool evaluates every link-priced gate — the
host-pileup genome bound (ops.pileup.host_pileup_max_len), the tail
routing crossover (backends.jax_backend._tail_cpu_wins), and the
output-encoding pick (_fetch_costs) — for each BASELINE.md workload
shape under the bench rig's measured tunnel constants (65 ms RT,
40 MB/s) and PCIe-class constants (1 ms RT, 2 GB/s), asserts the flips
are COHERENT (everything device-side on the fast link for large
genomes, host-side on the tunnel), and emits one JSON line per
(config, link) cell plus a summary.

This is the offline half of the evidence; the campaign's
``fastlink_bench`` step additionally runs a forced-constant bench row
on the real chip so the flipped decisions appear in a measured row's
``pileup``/``tail_device`` fields.

Run:  python tools/fastlink_matrix.py > campaign/fastlink_matrix_r05.json
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["S2C_LINK_PROBE"] = "0"

from sam2consensus_tpu.utils.platform import pin_platform_from_env  # noqa
pin_platform_from_env()


#: (name, total_len, aligned_bases, n_thresholds) — BASELINE.md shapes
CONFIGS = [
    ("phix", 5_386, 2_000_000, 1),
    ("amplicon_deep", 400, 8_000_000, 1),
    ("ecoli_scale", 4_600_000, 15_000_000, 1),
    ("north_star", 1_000_000, 100_000_000, 1),
    ("wide_genome", 40_000_000, 10_000_000, 1),
]

LINKS = {
    # the bench rig's measured tunnel (tools/tunnel_probe.py round 4)
    "tunnel": {"rt_ms": 65.0, "mbps": 40.0},
    # PCIe-class TPU-VM link
    "pcie": {"rt_ms": 1.0, "mbps": 2000.0},
}


def evaluate(link: dict) -> list:
    os.environ["S2C_TAIL_RT_MS"] = str(link["rt_ms"])
    os.environ["S2C_TAIL_LINK_MBPS"] = str(link["mbps"])
    from sam2consensus_tpu.backends import jax_backend as jb
    from sam2consensus_tpu.ops import fused
    from sam2consensus_tpu.ops.pileup import host_pileup_max_len

    rows = []
    bps = link["mbps"] * 1e6
    for name, total_len, aligned, n_thr in CONFIGS:
        bound = host_pileup_max_len(True, link_free=False, link_bps=bps)
        pileup_route = "host" if total_len <= bound else "device"
        cpu_tail = jb._tail_cpu_wins(total_len, n_thr, total_len * 6,
                                     True, aligned_bases=aligned)
        sparse_cap = fused.pad_cap(min(total_len, aligned) + 1)
        costs = jb._fetch_costs(total_len, n_thr, sparse_cap, bps)
        pick = min(costs, key=costs.get)
        enc = ("dense" if pick is None
               else "packed5" if pick == "packed5" else "sparse")
        rows.append({
            "config": name, "total_len": total_len,
            "aligned_bases": aligned,
            "host_pileup_bound": int(min(bound, 1 << 62)),
            "pileup_route": pileup_route,
            "tail": "cpu" if cpu_tail else "device",
            "out_encoding": enc,
        })
    return rows


def main():
    result = {"links": LINKS, "cells": {}}
    for lname, link in LINKS.items():
        result["cells"][lname] = evaluate(link)
    by = {ln: {r["config"]: r for r in rows}
          for ln, rows in result["cells"].items()}

    # coherence checks (the artifact's point): EVERY link-priced gate
    # must flip device-side together on the fast link for the large
    # genomes, and host-side together on the tunnel
    checks = {
        # tunnel: the slow-link bypass unbounds the host-pileup gate,
        # and every tail routes to the local cpu (native vote)
        "tunnel_pileup_host_everywhere": all(
            r["pileup_route"] == "host" for r in result["cells"]["tunnel"]),
        "tunnel_tail_cpu_everywhere": all(
            r["tail"] == "cpu" for r in result["cells"]["tunnel"]),
        # pcie: large genomes cross the narrow bound -> device pileup
        "pcie_wide_pileup_device":
            by["pcie"]["wide_genome"]["pileup_route"] == "device",
        # pcie: device tails win from ecoli scale up
        "pcie_ecoli_tail_device": by["pcie"]["ecoli_scale"]["tail"]
            == "device",
        "pcie_wide_tail_device": by["pcie"]["wide_genome"]["tail"]
            == "device",
        "pcie_north_star_tail_device": by["pcie"]["north_star"]["tail"]
            == "device",
        # output encoding: the fast link ships dense ASCII (the decode
        # passes stop paying for saved wire); the tunnel picks a packed
        # encoding for every genome large enough to matter
        "pcie_dense_everywhere": all(
            r["out_encoding"] == "dense"
            for r in result["cells"]["pcie"]),
        "tunnel_packs_large_genomes": all(
            by["tunnel"][c]["out_encoding"] != "dense"
            for c in ("ecoli_scale", "north_star", "wide_genome")),
    }
    result["coherence"] = checks
    result["coherent"] = all(checks.values())
    print(json.dumps(result, indent=1))
    return 0 if result["coherent"] else 1


if __name__ == "__main__":
    sys.exit(main())
