#!/usr/bin/env python3
"""Deterministic input-format fixtures: paired SAM / BAM / BGZF-SAM
corpora with pinned oracle outputs, committed under tests/data/.

Every fixture family is generated from a SEEDED simulator (no clocks,
no environment, no htslib) and written by the pure-stdlib writers in
``sam2consensus_tpu/formats`` — so a regenerate is byte-identical and
the tool is an idempotent campaign step (existing, digest-matching
fixtures are left untouched; ``--force`` rewrites; a digest MISMATCH
exits 1, because it means the generators drifted from the committed
corpus and tests downstream are pinning stale bytes).

Families:

* ``formats_short``   — short reads, mixed indels/clips, 3 contigs; the
  SAM↔BAM↔BGZF equivalence corpus.
* ``formats_longread``— ONT/PacBio-like dense-indel long reads (3 kb,
  ~20 indel events each): exercises the segmented slab layout and the
  insertion table under long-CIGAR stress.
* ``formats_adversarial`` — hand-built records: a read wider than any
  slab bucket, an insertion run > 255 bases, an all-indel read (zero
  M ops), a POS-0 leading-deletion read, and an end-anchored read.

Each family writes ``<stem>.sam``, ``<stem>.bam``, ``<stem>.sam.gz``
(BGZF), ``<stem>.plain.sam.gz`` (single-member gzip, the serial-decode
control) and ``<stem>.expected.fasta`` — the CPU golden oracle's
rendered output (t=0.25, no wrap), the byte-identity target every
format path must hit.
"""

import argparse
import gzip
import hashlib
import io
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sam2consensus_tpu.backends.cpu import CpuBackend  # noqa: E402
from sam2consensus_tpu.config import RunConfig  # noqa: E402
from sam2consensus_tpu.formats.bam import sam_text_to_bam  # noqa: E402
from sam2consensus_tpu.formats.bgzf import write_bgzf  # noqa: E402
from sam2consensus_tpu.io.fasta import render_file  # noqa: E402
from sam2consensus_tpu.io.sam import ReadStream, read_header  # noqa: E402
from sam2consensus_tpu.utils.simulate import (SimSpec, sam_text,  # noqa: E402
                                              simulate)

DATA_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "data")


def adversarial_text() -> str:
    """Hand-specified records targeting the long-read escape lanes."""
    contigs = [("adv0", 9000), ("adv1", 600)]
    reads = [
        # 1. wider than any default slab bucket (span 8000 > 4096):
        #    splits into segment rows under the segmented layout
        ("adv0", 101, "8000M", "A" * 8000),
        # 2. insertion run > 255 (motif length 300) — n_cols / delta8
        #    escape-lane stress at one site
        ("adv0", 501, "100M300I100M", "C" * 100 + "G" * 300 + "T" * 100),
        # 3. all-indel read: zero M ops — span comes entirely from D,
        #    SEQ is consumed by I/S only
        ("adv0", 1001, "40I200D10S", "A" * 50),
        # 4. leading deletion at POS 1 (0-based 0) — gap-start row
        ("adv1", 1, "30D50M", "N" * 50),
        # 5. end-anchored read, exact tail fit
        ("adv1", 551, "50M", "G" * 50),
        # 6. deep stack over the >255-insertion site so coverage
        #    completion (quirk 4) goes through the escape lane too
        *[("adv0", 501, "200M", "A" * 200) for _ in range(7)],
        # 7. an unmapped record (CIGAR "*"), skipped but counted
        ("adv0", 1, "*", "*"),
    ]
    return sam_text(contigs, reads)


FAMILIES = {
    "formats_short": lambda: simulate(SimSpec(
        n_contigs=3, contig_len=700, n_reads=420, read_len=80,
        ins_read_rate=0.12, del_read_rate=0.12, softclip_rate=0.08,
        seed=1401, contig_prefix="fshort")),
    "formats_longread": lambda: simulate(SimSpec(
        n_contigs=2, contig_len=22000, n_reads=64, read_len=3000,
        n_indels=20, max_indel=6, contig_len_jitter=0.0,
        seed=1402, contig_prefix="ont")),
    "formats_adversarial": adversarial_text,
}


def oracle_fasta(text: str) -> str:
    handle = io.StringIO(text)
    contigs, _n, first = read_header(handle)
    cfg = RunConfig(prefix="fixture", outfolder="./")
    res = CpuBackend().run(contigs, ReadStream(handle, first), cfg)
    return "".join(render_file(res.fastas[name], 0)
                   for name in (c.name for c in contigs)
                   if name in res.fastas)


def build_family(stem: str, text: str) -> dict:
    """All artifact payloads for one family, as {filename: bytes}."""
    out = {f"{stem}.sam": text.encode("ascii")}
    from sam2consensus_tpu.formats.bam import (bam_payload,
                                               sam_text_to_records)
    from sam2consensus_tpu.formats.bgzf import BGZF_EOF, compress_block

    # the SAME parse the bench converter uses (formats/bam.py), so the
    # committed fixtures can never drift from in-bench conversions
    payload = bam_payload(*sam_text_to_records(text))
    frames = [compress_block(payload[o:o + 60000])
              for o in range(0, len(payload), 60000)]
    out[f"{stem}.bam"] = b"".join(frames) + BGZF_EOF
    # BGZF-compressed SAM (small blocks so even the tiny fixtures span
    # multiple blocks — the parallel-inflate path gets real work)
    data = text.encode("ascii")
    bgzf_frames = [compress_block(data[o:o + 16384])
                   for o in range(0, len(data), 16384)]
    out[f"{stem}.sam.gz"] = b"".join(bgzf_frames) + BGZF_EOF
    # plain single-member gzip control (mtime pinned: deterministic)
    buf = io.BytesIO()
    with gzip.GzipFile(fileobj=buf, mode="wb", mtime=0) as gz:
        gz.write(data)
    out[f"{stem}.plain.sam.gz"] = buf.getvalue()
    out[f"{stem}.expected.fasta"] = oracle_fasta(text).encode("ascii")
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--force", action="store_true",
                    help="rewrite fixtures even when they exist and match")
    ap.add_argument("--out", default=DATA_DIR,
                    help=f"output directory (default {DATA_DIR})")
    args = ap.parse_args(argv)

    os.makedirs(args.out, exist_ok=True)
    wrote = kept = 0
    drifted = []
    for stem, gen in sorted(FAMILIES.items()):
        payloads = build_family(stem, gen())
        for name, blob in sorted(payloads.items()):
            path = os.path.join(args.out, name)
            if os.path.exists(path) and not args.force:
                with open(path, "rb") as fh:
                    have = fh.read()
                if have == blob:
                    kept += 1
                    continue
                drifted.append(name)
                print(f"DRIFT {name}: committed "
                      f"{hashlib.sha256(have).hexdigest()[:12]} vs "
                      f"regenerated "
                      f"{hashlib.sha256(blob).hexdigest()[:12]}",
                      file=sys.stderr)
                continue
            with open(path, "wb") as fh:
                fh.write(blob)
            wrote += 1
            print(f"wrote {name} ({len(blob)} B, sha256 "
                  f"{hashlib.sha256(blob).hexdigest()[:12]})")
    print(f"fixtures: {wrote} written, {kept} verified-unchanged, "
          f"{len(drifted)} drifted")
    if drifted:
        print("generator/fixture drift — regenerate with --force and "
              "review the diff", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
