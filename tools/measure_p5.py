"""On-chip measurement of the packed5 output encoding vs dense.

Times ``ops.fused.vote_packed_simple`` with ``out_enc=None`` (dense)
and ``out_enc="packed5"`` at two genome scales, splitting dispatch
(block_until_ready) from fetch, and prints one JSON line per variant
plus a derived device-side cost in ns/char — the number that belongs in
``S2C_P5_DEV_NS`` (backends/jax_backend.py P5_DEV_NS_PER_CHAR).  Run on
the real chip; compiles are warmed before timing.
"""

from __future__ import annotations

import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sam2consensus_tpu.ops import fused
    from sam2consensus_tpu.ops.cutoff import encode_thresholds

    thr = jnp.asarray(encode_thresholds([0.25]))
    for length in (4_600_000, 40_000_000):
        key = jax.random.PRNGKey(0)
        cov_mask = jax.random.uniform(key, (length,)) < 0.25
        counts = (jnp.where(cov_mask[:, None], 3, 0).astype(jnp.uint8)
                  * jnp.ones((1, 6), jnp.uint8))
        counts.block_until_ready()
        offsets = jnp.asarray(np.array([0, length], dtype=np.int32))
        results = {}
        for tag, enc in (("dense", None), ("packed5", "packed5")):
            out = fused.vote_packed_simple(counts, thr, offsets, 1, enc)
            out.block_until_ready()
            np.asarray(out)                       # warm compile + fetch
            best_c, best_f = 1e9, 1e9
            for _ in range(2):
                t0 = time.perf_counter()
                out = fused.vote_packed_simple(counts, thr, offsets, 1,
                                               enc)
                out.block_until_ready()
                t1 = time.perf_counter()
                host = np.asarray(out)
                t2 = time.perf_counter()
                best_c, best_f = min(best_c, t1 - t0), min(best_f, t2 - t1)
            results[tag] = (best_c, best_f)
            print(json.dumps({
                "L": length, "enc": tag, "compute_sec": round(best_c, 4),
                "fetch_sec": round(best_f, 4),
                "bytes": int(host.nbytes)}), flush=True)
        dev_delta = results["packed5"][0] - results["dense"][0]
        print(json.dumps({
            "L": length,
            "p5_dev_ns_per_char": round(dev_delta / length * 1e9, 2),
            "p5_total_sec": round(sum(results["packed5"]), 4),
            "dense_total_sec": round(sum(results["dense"]), 4)}),
            flush=True)


if __name__ == "__main__":
    main()
