#!/usr/bin/env python3
"""Fleet what-if: score the evidence plane against a journaled soak.

The ISSUE 19 acceptance harness for the rate-card / burn-alert /
scale-hint plane (observability/ratecard.py, observability/burn.py):
one journaled two-round soak with a hung tenant and a worker restart,
replayed in hindsight and scored against what the plane predicted —

* **round 1 (learn)** — worker ``w0`` drains a mixed queue (a fast
  tenant and a deliberately heavy "hung" tenant) on a fresh journal;
  its rate card learns the measured throughput constants and persists
  next to the journal at every job boundary.  An e2e objective is
  then chosen BETWEEN the two tenants' measured elapsed ranges (the
  harness never guesses machine speed), and ``burn.replay_burn``
  re-scores the committed events with their wall stamps: the hung
  tenant must PAGE, the fast tenant must stay OK;
* **restart (churn)** — ``w0``'s second life loads the persisted card
  (restart epoch bumped, sample counts and age stamps intact — the
  SIGKILL-survival claim: the card was durable at the last job
  boundary, nothing depended on a clean shutdown).  Replaying the
  shared journal feeds round 1's peer-committed breaches into the
  LIVE burn monitor with their commit stamps, so the second life
  pages the hung tenant before running a single job of its own;
* **round 2 (joined drain)** — the scale hint computed from the
  learned card BEFORE the round projects the queue's drain time; the
  journal then measures the actual drain; the residual must land
  within ``--band``.  The runner's own drain-episode join
  (``scale_hint`` band=0 ledger decision, ``fleet/drain_episodes``)
  must have fired;
* **byte identity** — round 1's committed FASTA set is sha256-equal
  to a plane-dark baseline of the same queue (no SLO, no confident
  card: every consult serves defaults) — the evidence plane never
  touches output bytes;
* **exposition** — the second life's rendered telemetry carries the
  ``restart_epoch`` label, the ``s2c_process_start_time_seconds``
  gauge and the ``s2c_rate_*`` families, and lints clean.

One JSON row per check + a ``"mode": "summary"`` row, as JSONL on
stdout (or ``--out``); exit 0 iff every check passed.  Campaign step
18 (tools/tpu_campaign.sh) commits the cpu-fallback artifact at
campaign/fleet_whatif_r06_cpufallback.jsonl, which rides
``tools/regress_check.py --jsonl`` and the structural
``tools/check_perf_claims.py`` lint (hint row present, residual
in-band, burn verdict matches the injected hang).

Usage: python tools/fleet_whatif.py [--fast-jobs 3] [--hung-jobs 2]
       [--reads 1500] [--hung-factor 8] [--band 6.0] [--out FILE]
"""

import argparse
import json
import math
import os
import shutil
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def sha_dir(d):
    from sam2consensus_tpu.serve.benchmark import _sha_dir

    return _sha_dir(d)


def _sim_inputs(work, tag, n_fast, n_hung, reads, hung_factor,
                contig_len, read_len, seed0):
    from sam2consensus_tpu.utils.simulate import SimSpec, simulate

    jobs = []
    for k in range(n_fast + n_hung):
        hung = k >= n_fast
        spec = SimSpec(
            n_contigs=1,
            contig_len=contig_len * (2 if hung else 1),
            n_reads=reads * (hung_factor if hung else 1),
            read_len=read_len, contig_len_jitter=0.0,
            seed=seed0 + k, contig_prefix=f"wi{tag}{k:02d}_")
        p = os.path.join(work, f"{tag}_job{k}.sam")
        with open(p, "w") as fh:
            fh.write(simulate(spec))
        jobs.append((p, "hung" if hung else "fast"))
    return jobs


def _specs(jobs, outdir, tag):
    from sam2consensus_tpu.config import RunConfig
    from sam2consensus_tpu.serve import JobSpec

    specs = []
    for k, (path, tenant) in enumerate(jobs):
        cfg = RunConfig(backend="jax", pileup="scatter", shards=1,
                        outfolder=outdir + "/", prefix=f"{tag}{k}")
        specs.append(JobSpec(filename=path, config=cfg,
                             job_id=f"{tag}{k}", tenant=tenant))
    return specs


def _runner(**kw):
    from sam2consensus_tpu.serve import ServeRunner

    kw.setdefault("prewarm", "off")
    kw.setdefault("persistent_cache", False)
    return ServeRunner(**kw)


def _journal_events(jdir):
    from sam2consensus_tpu.serve.journal import JobJournal

    return JobJournal(jdir, checkpoint_every=0).events()


def _elapsed_by_tenant(events):
    out = {}
    for e in events:
        if e.get("ev") == "committed" and "elapsed_sec" in e:
            out.setdefault(e.get("tenant") or "default", []).append(
                float(e["elapsed_sec"]))
    return out


def _drain_sec(events, keys):
    """Journal-measured drain of a key set: first submit stamp to
    last commit stamp (wall, from the events' own ``t``)."""
    subs = [float(e["t"]) for e in events
            if e.get("ev") == "submitted" and e.get("key") in keys]
    coms = [float(e["t"]) for e in events
            if e.get("ev") == "committed" and e.get("key") in keys]
    if not subs or not coms:
        return None
    return max(coms) - min(subs)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--fast-jobs", type=int, default=3)
    ap.add_argument("--hung-jobs", type=int, default=2)
    ap.add_argument("--reads", type=int, default=1500)
    ap.add_argument("--hung-factor", type=int, default=8,
                    help="hung-tenant jobs carry this many times the "
                         "fast tenant's reads (the injected 'hang' is "
                         "honest slowness, not a sleep)")
    ap.add_argument("--contig-len", type=int, default=3000)
    ap.add_argument("--read-len", type=int, default=100)
    ap.add_argument("--band", type=float, default=6.0,
                    help="scale-hint drain residual band "
                         "(measured/projected within [1/band, band])")
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    if args.hung_jobs < 2:
        ap.error("--hung-jobs must be >= 2 (one breach is a blip the "
                 "hysteresis is REQUIRED to ignore)")

    import tempfile

    from sam2consensus_tpu.observability import burn as oburn
    from sam2consensus_tpu.observability import ratecard as orc
    from sam2consensus_tpu.observability import telemetry as otele

    work = args.workdir or tempfile.mkdtemp(prefix="s2c_whatif_")
    os.makedirs(work, exist_ok=True)
    os.environ.setdefault("S2C_JIT_CACHE",
                          os.path.join(work, "_jit_cache"))
    log(f"[fleet_whatif] workdir {work}")

    rows = []
    failures = 0

    def check(name, ok, **fields):
        nonlocal failures
        failures += 0 if ok else 1
        rows.append({"check": name, "ok": bool(ok), **fields})
        log(f"[fleet_whatif] {name}: " + ("OK" if ok else "FAIL")
            + (f" {fields}" if not ok else ""))

    q1 = _sim_inputs(work, "a", args.fast_jobs, args.hung_jobs,
                     args.reads, args.hung_factor, args.contig_len,
                     args.read_len, seed0=8100)
    q2 = _sim_inputs(work, "b", args.fast_jobs, args.hung_jobs,
                     args.reads, args.hung_factor, args.contig_len,
                     args.read_len, seed0=8200)

    # -- plane-dark baseline: the byte-identity oracle ----------------
    base_out = os.path.join(work, "out_base")
    os.makedirs(base_out, exist_ok=True)
    r = _runner(journal_dir=os.path.join(work, "j_base"))
    try:
        res = r.submit_jobs(_specs(q1, base_out, "a"))
        base_ok = all(x.ok for x in res)
    finally:
        r.close()
    want = sha_dir(base_out)
    log(f"[fleet_whatif] baseline: {len(want)} output file(s)")

    jdir = os.path.join(work, "j_soak")
    out1 = os.path.join(work, "out_r1")
    os.makedirs(out1, exist_ok=True)

    # -- round 1: w0 life 1 learns + commits the mixed queue ----------
    t0 = time.monotonic()
    r = _runner(journal_dir=jdir, worker_id="w0", lease_ttl=30.0)
    try:
        res1 = r.submit_jobs(_specs(q1, out1, "a"))
        r1_ok = all(x.ok for x in res1)
        card_file = orc.card_path(r.journal.root, "w0")
    finally:
        r.close()
    r1_sec = time.monotonic() - t0
    check("round1_drain", r1_ok, jobs=len(q1),
          drain_sec=round(r1_sec, 3))

    got = sha_dir(out1)
    check("byte_identity_plane_on_vs_off", got == want and base_ok,
          files=len(got))

    # -- the persisted card: durable at the last job boundary ---------
    card_blob = None
    if os.path.exists(card_file):
        with open(card_file) as fh:
            card_blob = json.load(fh)
    warm = ((card_blob or {}).get("rates") or {}).get(
        "warm_jobs_per_sec") or {}
    check("card_persisted", card_blob is not None
          and card_blob.get("schema") == orc.SCHEMA
          and int(warm.get("n", 0)) >= len(q1)
          and float(warm.get("updated_unix", 0)) > 0,
          path=os.path.basename(card_file),
          samples=int(warm.get("n", 0)))

    # -- choose the objective from the journal's own measurements -----
    events = _journal_events(jdir)
    by_tenant = _elapsed_by_tenant(events)
    fast_max = max(by_tenant.get("fast") or [0.0])
    hung_min = min(by_tenant.get("hung") or [float("inf")])
    separated = 0.0 < fast_max < hung_min < float("inf")
    objective = round(math.sqrt(fast_max * hung_min), 3) \
        if separated else None
    check("tenant_separation", separated,
          fast_max_sec=round(fast_max, 3),
          hung_min_sec=round(hung_min, 3) if hung_min < 1e9 else None,
          e2e_objective_sec=objective)

    # -- hindsight burn verdicts over the committed journal -----------
    verdict = {}
    if objective:
        rb = oburn.replay_burn(events, {"e2e": objective})
        verdict = rb["states"]
        check("burn_replay_verdicts",
              verdict.get("hung") == "page"
              and verdict.get("fast") == "ok",
              states=verdict, e2e_objective_sec=objective)
    else:
        check("burn_replay_verdicts", False, states={},
              reason="no separated objective")

    # -- restart: w0 life 2 — card ages intact, live burn from replay -
    hint = None
    hint_resid = None
    lint_errs = None
    r2_ok = False
    expo_ok = False
    joined = 0
    live_states = {}
    restarts = None
    out2 = os.path.join(work, "out_r2")
    os.makedirs(out2, exist_ok=True)
    r = _runner(journal_dir=jdir, worker_id="w0", lease_ttl=30.0,
                slo=f"e2e={objective}s" if objective else None)
    try:
        restarts = r.ratecard.restarts
        snap = r.ratecard.snapshot()
        w = snap["rates"].get("warm_jobs_per_sec") or {}
        check("card_restart_survival", restarts == 1
              and int(w.get("n", 0)) >= len(q1)
              and w.get("age_sec") is not None
              and w.get("confident") is True,
              restarts=restarts, samples=int(w.get("n", 0)),
              age_sec=w.get("age_sec"))

        # the hint BEFORE round 2: projected drain for the new queue
        hint = orc.compute_scale_hint([snap], queue_depth=len(q2),
                                      workers=1)
        res2 = r.submit_jobs(_specs(q2, out2, "b"))
        r2_ok = all(x.ok for x in res2)
        live_states = dict(r.burn.states())
        joined = int(r.registry.value("fleet/drain_episodes"))
        expo = r.render_telemetry()
        lint_errs = otele.lint_openmetrics(expo)
        expo_ok = (lint_errs == []
                   and f'restart_epoch="{restarts}"' in expo
                   and "s2c_process_start_time_seconds" in expo
                   and 's2c_rate{key="warm_jobs_per_sec"' in expo
                   and "s2c_burn_alert_state" in expo)
    finally:
        r.close()

    check("burn_live_after_restart",
          live_states.get("hung") == "page"
          and live_states.get("fast") == "ok",
          states=live_states)
    check("exposition_lint", bool(expo_ok),
          errors=(lint_errs or [])[:5], restart_epoch=restarts)

    # -- round 2 measured drain vs the hint's projection --------------
    events2 = _journal_events(jdir)
    # round 2 keys: submitted events NOT present in round 1's scan
    r1_keys = {e.get("key") for e in events
               if e.get("ev") == "submitted"}
    keys2 = {e.get("key") for e in events2
             if e.get("ev") == "submitted"
             and e.get("key") not in r1_keys}
    measured = _drain_sec(events2, keys2)
    projected = (hint or {}).get("projected_drain_sec")
    if measured and projected:
        hint_resid = round(measured / projected, 4)
    check("scale_hint_drain_join",
          r2_ok and hint is not None and projected is not None
          and measured is not None
          and hint_resid is not None
          and 1.0 / args.band <= hint_resid <= args.band
          and joined >= 1,
          verdict=(hint or {}).get("verdict"),
          reason=(hint or {}).get("reason"),
          projected_drain_sec=projected,
          measured_drain_sec=round(measured, 3) if measured else None,
          residual=hint_resid, band=args.band,
          drain_episodes_joined=joined)

    summary = {
        "mode": "summary",
        "fast_jobs": args.fast_jobs, "hung_jobs": args.hung_jobs,
        "reads": args.reads, "hung_factor": args.hung_factor,
        "e2e_objective_sec": objective,
        "burn_verdicts": verdict,
        "burn_live_verdicts": live_states,
        "card_restarts": restarts,
        "hint_verdict": (hint or {}).get("verdict"),
        "hint_projected_drain_sec": (hint or {}
                                     ).get("projected_drain_sec"),
        "hint_measured_drain_sec": round(measured, 3)
        if measured else None,
        "hint_residual": hint_resid,
        "residual_band": args.band,
        "identical_all": got == want,
        "checks": len(rows),
        "failures": failures,
        "host_cores": os.cpu_count(),
        "platform": os.environ.get("JAX_PLATFORMS", ""),
    }
    lines = [json.dumps(x) for x in rows] + [json.dumps(summary)]
    blob = "\n".join(lines) + "\n"
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(blob)
        log(f"[fleet_whatif] wrote {args.out}")
    else:
        sys.stdout.write(blob)
    if not args.workdir:
        shutil.rmtree(work, ignore_errors=True)
    log(f"[fleet_whatif] {len(rows)} checks, {failures} failure(s)")
    return 0 if failures == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
