#!/usr/bin/env python3
"""Cold-vs-warm serving benchmark (the PR-5 serve tentpole's evidence).

Runs a batch of small consensus jobs two ways — one CLI process per job
(cold, the pre-serve reality) and one persistent ServeRunner (warm) —
over byte-compared outputs, and writes one JSON row per job plus a
summary row as JSONL (``--out``; stdout otherwise).  The summary's
``speedup_vs_cold``/``identical`` fields are the acceptance numbers;
``jit_hit``/``jit_miss``/``overlap_sec`` per warm row are the why.

Campaign usage (tools/tpu_campaign.sh step ``serve_bench``) tags the
artifact per round; the CPU-fallback harness proof lives at
campaign/serve_bench_r06_cpufallback.jsonl.

Usage: python tools/serve_bench.py [--jobs 8] [--reads 5000]
       [--contig-len 5386] [--pileup scatter] [--out FILE.jsonl]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--jobs", type=int, default=8)
    ap.add_argument("--reads", type=int, default=5000)
    ap.add_argument("--contig-len", type=int, default=5386)
    ap.add_argument("--read-len", type=int, default=100)
    ap.add_argument("--pileup", default="scatter",
                    choices=["auto", "scatter", "pallas", "mxu", "host"])
    ap.add_argument("--cold-timeout", type=int, default=600,
                    help="per-cold-job subprocess timeout (seconds)")
    ap.add_argument("--out", default=None,
                    help="JSONL destination (default: stdout)")
    args = ap.parse_args(argv)

    from sam2consensus_tpu.utils.platform import pin_platform_from_env

    pin_platform_from_env()

    from sam2consensus_tpu.serve.benchmark import run_serve_bench

    res = run_serve_bench(n_jobs=args.jobs, n_reads=args.reads,
                          contig_len=args.contig_len,
                          read_len=args.read_len, pileup=args.pileup,
                          cold_timeout=args.cold_timeout, log=log)
    lines = [json.dumps(r) for r in res["rows"]]
    lines.append(json.dumps(res["summary"]))
    blob = "\n".join(lines) + "\n"
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(blob)
        log(f"[serve_bench] wrote {args.out}")
    else:
        sys.stdout.write(blob)
    s = res["summary"]
    return 0 if (s["identical"] and s["warm_per_job_sec"] > 0) else 1


if __name__ == "__main__":
    sys.exit(main())
