#!/bin/bash
# CPU dress rehearsal of the full-scale bench configs; aborts the moment
# the campaign reports the tunnel UP so it never contends with the real
# bench on this one-core host.
cd /root/repo
mkdir -p campaign
JAX_PLATFORMS=cpu BENCH_INIT_TIMEOUT=30 BENCH_INIT_RETRIES=1 \
  BENCH_CONFIGS=north_star,wide_genome \
  timeout -k 30 2400 python bench.py > campaign/rehearsal.json \
  2> campaign/rehearsal_stderr.log &
BPID=$!
# only react to "tunnel UP" lines appended AFTER this rehearsal started —
# campaign.log persists across campaigns, so a historical match must not
# abort a fresh rehearsal
LOG_OFFSET=$(wc -c < campaign/campaign.log 2>/dev/null || echo 0)
while kill -0 $BPID 2>/dev/null; do
  if tail -c +$((LOG_OFFSET + 1)) campaign/campaign.log 2>/dev/null \
      | grep -q "tunnel UP"; then
    kill -TERM $BPID 2>/dev/null
    echo "aborted: tunnel came up" >> campaign/rehearsal_stderr.log
    exit 0
  fi
  sleep 20
done
echo "rehearsal done" >> campaign/rehearsal_stderr.log
