#!/usr/bin/env python3
"""BGZF block-parallel inflate: thread-scaling measurement.

The formats tentpole's decode-side claim is that BGZF's independently
deflated ≤64 KiB blocks are free parallel-decode shards.  This tool
measures that on the CURRENT host — raw ordered-reassembly inflate
throughput (``formats/bgzf.py BgzfReader.read``) and end-to-end BAM
ingest decode seconds at each thread count, with the host's core count
recorded so the artifact is honest about whether scaling was possible
at all (the convention tools/thread_scaling.py set).  One JSON line per
measurement; serial gzip and the BGZF-SAM/native-text path ride along
as controls.

Usage: python tools/bgzf_scaling.py [> perf/bgzf_scaling_<r>.jsonl]
Env: S2C_SCALING_THREADS=1,2,4  BGZF_SCALING_READS=150000
"""

import gzip
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def emit(row):
    row["host_cores"] = os.cpu_count()
    print(json.dumps(row), flush=True)


def main():
    from sam2consensus_tpu.config import RunConfig
    from sam2consensus_tpu.formats import open_alignment_input
    from sam2consensus_tpu.formats.bam import sam_text_to_bam
    from sam2consensus_tpu.formats.bgzf import BgzfReader, write_bgzf
    from sam2consensus_tpu.utils.simulate import SimSpec, simulate

    threads_list = [int(t) for t in os.environ.get(
        "S2C_SCALING_THREADS", "1,2,4").split(",")]
    n_reads = int(os.environ.get("BGZF_SCALING_READS", "150000"))

    spec = SimSpec(n_contigs=1, contig_len=4_600_000, n_reads=n_reads,
                   read_len=100, ins_read_rate=0.05, del_read_rate=0.05,
                   contig_len_jitter=0.0, seed=404,
                   contig_prefix="ecoli")
    log(f"[sim] {n_reads} reads ...")
    text = simulate(spec)
    data = text.encode("ascii")
    tmp = tempfile.mkdtemp(prefix="bgzf_scaling_")
    bgz = os.path.join(tmp, "e.sam.gz")
    write_bgzf(data, bgz)
    bam = os.path.join(tmp, "e.bam")
    sam_text_to_bam(text, bam)
    total_mb = len(data) / 1e6

    # --- raw inflate: serial gzip control ---
    pgz = os.path.join(tmp, "e.plain.sam.gz")
    with gzip.open(pgz, "wb") as fh:
        fh.write(data)
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        with gzip.open(pgz, "rb") as fh:
            out = fh.read()
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    assert out == data
    emit({"metric": "inflate", "container": "gzip", "threads": 1,
          "sec": round(best, 4), "mb_per_s": round(total_mb / best, 1),
          "mb": round(total_mb, 1)})
    log(f"[inflate] gzip serial: {best:.3f}s "
        f"({total_mb / best:.0f} MB/s)")

    # --- raw inflate: BGZF at each thread count ---
    for nt in threads_list:
        best = None
        for _ in range(3):
            r = BgzfReader(bgz, threads=nt)
            t0 = time.perf_counter()
            out = r.read()
            dt = time.perf_counter() - t0
            r.close()
            best = dt if best is None else min(best, dt)
        assert out == data
        emit({"metric": "inflate", "container": "bgzf", "threads": nt,
              "sec": round(best, 4),
              "mb_per_s": round(total_mb / best, 1),
              "mb": round(total_mb, 1)})
        log(f"[inflate] bgzf threads={nt}: {best:.3f}s "
            f"({total_mb / best:.0f} MB/s)")

    # --- end-to-end ingest decode seconds (jax backend, host pileup) ---
    from sam2consensus_tpu.backends.jax_backend import JaxBackend

    be = JaxBackend()
    for label, path in (("bam", bam), ("bgzf_sam", bgz)):
        for nt in threads_list:
            best = None
            for _ in range(3):
                ai = open_alignment_input(path, binary=True, threads=nt)
                cfg = RunConfig(prefix="s", backend="jax",
                                decode_threads=nt)
                res = be.run(ai.contigs, ai.stream, cfg)
                ai.close()
                d = res.stats.extra.get("decode_sec", 0.0)
                best = d if best is None else min(best, d)
            emit({"metric": "ingest_decode", "format": label,
                  "threads": nt, "decode_sec": round(best, 4),
                  "reads": n_reads})
            log(f"[ingest] {label} threads={nt}: decode {best:.3f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
